"""Recompute (gradient checkpointing) — SURVEY §2.12.

Ref: the reference's forward-recomputation machinery
(python/paddle/fluid/incubate/fleet RecomputeOptimizer / recompute
segments). TPU-native: ``jax.checkpoint`` on the sub-graph — the forward
runs normally, residuals inside the segment are dropped, and the backward
pass rematerializes them from the segment inputs. Trades FLOPs for HBM,
the standard lever for deep transformer stacks on TPU.

Works in eager mode and (the real use) inside the fused TrainStep trace:
the whole recompute region becomes one tape node whose vjp is the
jax.checkpoint'd vjp.

Limitation: the segment must be functionally pure w.r.t. its parameters —
buffer mutations inside (e.g. BatchNorm running stats) do not propagate
out of the recompute region. Transformer blocks (LayerNorm) are fine.
"""
from __future__ import annotations

import jax

from ..core import dispatch
from ..core.tensor import Tensor, Parameter

__all__ = ["recompute", "Recompute"]


def _segment_params(function, models):
    from ..nn.layer import Layer

    layers = []
    if isinstance(function, Layer):
        layers.append(function)
    for m in models or ():
        layers.append(m)
    params, seen = [], set()
    for layer in layers:
        for _, p in layer.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                params.append(p)
        for _, b in layer.named_buffers():
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                params.append(b)
    return params


def recompute(function, *args, models=None, **kwargs):
    """Run ``function(*args)`` under gradient checkpointing.

    function: a Layer (its parameters are discovered automatically) or any
    callable over Tensors (pass the Layers it closes over via ``models``).
    """
    from .jit import _rebind

    params = _segment_params(function, models)
    n = len(params)

    def pure(*arrays):
        p_arr, x_arr = list(arrays[:n]), arrays[n:]
        with _rebind(params, p_arr), dispatch.fresh_tape():
            ts = [Tensor(a, _internal=True) for a in x_arr]
            out = function(*ts, **kwargs)
            if isinstance(out, (tuple, list)):
                return tuple(o._data if isinstance(o, Tensor) else o
                             for o in out)
            return out._data if isinstance(out, Tensor) else out

    wrapped = jax.checkpoint(pure)
    return dispatch.apply("recompute", wrapped, *params, *args)


class Recompute:
    """Layer wrapper: ``Recompute(block)(x)`` == block(x) with segment
    checkpointing (ref: RecomputeOptimizer's segment list)."""

    def __init__(self, layer):
        self._layer = layer

    def __call__(self, *args, **kwargs):
        return recompute(self._layer, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layer"], name)
