"""Weight-decay regularizers (ref: python/paddle/fluid/regularizer.py).

Applied as a grad transform g + d(reg)/dp — matching the reference's
append_regularization_ops semantics (coupled decay; AdamW does decoupled
decay itself).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __call__(self, param, grad):
        return grad + self.coeff * jnp.sign(param)


class L2Decay(WeightDecayRegularizer):
    def __call__(self, param, grad):
        return grad + self.coeff * param


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
