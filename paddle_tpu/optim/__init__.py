"""paddle_tpu.optim — optimizers, LR schedulers, clipping, regularizers.

Mirrors ``paddle.optimizer`` + ``fluid/optimizer.py``/``clip.py``/
``regularizer.py``.
"""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adagrad, Adadelta, RMSProp, Adam, AdamW,
    Adamax, Lamb, Ftrl, ExponentialMovingAverage, LookAhead,
    DecayedAdagrad, Dpsgd, LarsMomentum, DGCMomentum, ModelAverage,
    RecomputeOptimizer, PipelineOptimizer,
)

# fluid-era *Optimizer names (ref: fluid/optimizer.py __all__)
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
DecayedAdagradOptimizer = DecayedAdagrad
DpsgdOptimizer = Dpsgd
LarsMomentumOptimizer = LarsMomentum
DGCMomentumOptimizer = DGCMomentum
LookaheadOptimizer = LookAhead
from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
from .regularizer import L1Decay, L2Decay  # noqa: F401
