"""Optimizers.

Ref: python/paddle/fluid/optimizer.py (SGD..Lamb + EMA/LookAhead wrappers)
and paddle/fluid/operators/optimizers/*.

Design: each rule is a pure function ``_update(p, g, state, lr) ->
(new_p, new_state)`` over jax arrays. Eager ``step()`` walks Parameters and
rebinds; the jitted train-step path (framework/jit.py) calls
``apply_gradients`` on whole pytrees so the optimizer update fuses into the
step executable together with forward+backward — one XLA program, donated
buffers, no per-op launches (the reference launches one CUDA kernel per
param per step).

``multi_precision`` keeps float32 master weights for bf16/fp16 params
(ref: mixed_precision master-weight behavior).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from .lr import LRScheduler
from .regularizer import L1Decay, L2Decay, WeightDecayRegularizer

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp", "Adam",
    "AdamW", "Adamax", "Lamb", "Ftrl", "ExponentialMovingAverage",
    "LookAhead", "DecayedAdagrad", "Dpsgd", "LarsMomentum", "DGCMomentum",
    "ModelAverage", "RecomputeOptimizer", "PipelineOptimizer",
]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._parameter_list = list(parameters) if parameters is not None else None
        self._learning_rate = learning_rate
        if isinstance(weight_decay, (int, float)):
            weight_decay = L2Decay(weight_decay)
        self._regularization = weight_decay
        self._explicit_grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict] = {}
        self._global_step = 0

    @property
    def _grad_clip(self):
        """Explicit clip wins; otherwise the process-wide default from
        fluid's set_gradient_clip(), resolved at USE time (the reference
        resolves it in minimize, so clips registered after optimizer
        construction must still apply)."""
        explicit = getattr(self, "_explicit_grad_clip", None)  # wrapper
        if explicit is not None:  # subclasses may skip Optimizer.__init__
            return explicit
        from .clip import get_gradient_clip

        return get_gradient_clip()

    @_grad_clip.setter
    def _grad_clip(self, value):
        self._explicit_grad_clip = value

    # -- lr -----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _param_groups(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        return self._parameter_list

    # -- state --------------------------------------------------------------
    def _state_for(self, p):
        key = p.name
        if key not in self._accumulators:
            s = self._init_state(p._data)
            if self._multi_precision and p._data.dtype in (jnp.bfloat16, jnp.float16):
                s["master"] = p._data.astype(jnp.float32)
            self._accumulators[key] = s
        return self._accumulators[key]

    def _init_state(self, p):
        return {}

    def _update(self, p, g, s, lr):
        raise NotImplementedError

    # -- the eager step -----------------------------------------------------
    def step(self):
        with dispatch.no_grad():
            pgs = [(p, p.grad._data if isinstance(p.grad, Tensor) else p.grad)
                   for p in self._param_groups
                   if p.trainable and p.grad is not None]
            if self._grad_clip is not None:
                pgs = self._grad_clip(pgs)
            base_lr = self.get_lr()
            for p, g in pgs:
                self._current_param = p
                reg = p.regularizer if p.regularizer is not None else self._regularization
                s = self._state_for(p)
                master = s.get("master")
                pw = master if master is not None else p._data
                g = g.astype(pw.dtype)
                if reg is not None and not isinstance(self, AdamW):
                    g = reg(pw, g)
                lr = base_lr * p.optimize_attr.get("learning_rate", 1.0)
                new_p, new_s = self._update(pw, g, s, lr)
                if master is not None:
                    new_s["master"] = new_p
                    p._replace(new_p.astype(p._data.dtype))
                else:
                    p._replace(new_p)
                self._accumulators[p.name] = new_s
        self._global_step += 1

    def clear_grad(self):
        for p in self._param_groups:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        tracer = dispatch.current_tracer()
        if tracer is not None:  # static-graph mode: delegate to the program
            from ..static_ import build_optimize_ops

            return build_optimize_ops(self, loss, parameters)
        if loss.stop_gradient:
            raise ValueError("loss has stop_gradient=True; nothing to minimize")
        loss.backward()
        self.step()
        return None, None

    # -- functional path (used inside jit) ----------------------------------
    def apply_gradients_tree(self, params, grads, states, lr=None):
        """Pure pytree update: (params, states) -> (new_params, new_states).

        params/grads: dict name->array; states: dict name->state-dict.
        Safe to call inside jax.jit — nothing here touches Python state.
        """
        lr = self.get_lr() if lr is None else lr
        new_p, new_s = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_p[name], new_s[name] = p, states.get(name, {})
                continue
            s = states.get(name) or self._init_state(p)
            np_, ns_ = self._update(p, g.astype(p.dtype), s, lr)
            new_p[name], new_s[name] = np_, ns_
        return new_p, new_s

    # -- serialization ------------------------------------------------------
    def state_dict(self):
        out = {}
        for pname, s in self._accumulators.items():
            for k, v in s.items():
                out[f"{pname}.{k}"] = np.asarray(v)
        out["@global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            out["@lr"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        for k, v in state.items():
            if k == "@global_step":
                self._global_step = int(v)
            elif k == "@lr":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(v)
            else:
                pname, slot = k.rsplit(".", 1)
                self._accumulators.setdefault(pname, {})[slot] = jnp.asarray(v)

    set_dict = set_state_dict


class SGD(Optimizer):
    def _update(self, p, g, s, lr):
        return p - lr * g, s


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update(self, p, g, s, lr):
        v = self._momentum * s["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {**s, "velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def _update(self, p, g, s, lr):
        m = s["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {**s, "moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def _update(self, p, g, s, lr):
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * g * g
        delta = jnp.sqrt((s["avg_squared_update"] + self._epsilon) /
                         (asg + self._epsilon)) * g
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * delta * delta
        return p - lr * delta, {**s, "avg_squared_grad": asg,
                                "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        s = {"mean_square": jnp.zeros_like(p), "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def _update(self, p, g, s, lr):
        ms = self._rho * s["mean_square"] + (1 - self._rho) * g * g
        ns = {**s, "mean_square": ms}
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * g
            ns["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * s["momentum"] + lr * g / denom
        ns["momentum"] = mom
        return p - mom, ns


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        f32 = jnp.float32
        return {"moment1": jnp.zeros(p.shape, f32),
                "moment2": jnp.zeros(p.shape, f32),
                "beta1_pow": jnp.ones((), f32),
                "beta2_pow": jnp.ones((), f32)}

    def _update(self, p, g, s, lr):
        gf = g.astype(jnp.float32)
        b1p = s["beta1_pow"] * self._beta1
        b2p = s["beta2_pow"] * self._beta2
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * gf * gf
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        step = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        new_p = (p.astype(jnp.float32) - step).astype(p.dtype)
        return new_p, {**s, "moment1": m, "moment2": v,
                       "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else weight_decay.coeff
        self._apply_decay_fn = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update(self, p, g, s, lr):
        # decoupled decay (ref: AdamW paper / paddle adamw_op);
        # apply_decay_param_fun excludes e.g. biases/LayerNorm by name, and
        # lr_ratio scales the per-param lr (layer-wise decay recipes)
        cur = getattr(self, "_current_param", None)
        if self._lr_ratio is not None and cur is not None:
            lr = lr * float(self._lr_ratio(cur))
        new_p, ns = super()._update(p, g, s, lr)
        if self._apply_decay_fn is not None and cur is not None and \
                not self._apply_decay_fn(cur.name):
            return new_p, ns
        decay = lr * self._coeff
        new_p = (new_p.astype(jnp.float32) -
                 decay * p.astype(jnp.float32)).astype(p.dtype)
        return new_p, ns


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p, jnp.float32),
                "inf_norm": jnp.zeros_like(p, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, s, lr):
        gf = g.astype(jnp.float32)
        b1p = s["beta1_pow"] * self._beta1
        m = self._beta1 * s["moment"] + (1 - self._beta1) * gf
        u = jnp.maximum(self._beta2 * s["inf_norm"], jnp.abs(gf))
        step = (lr / (1 - b1p)) * m / (u + self._epsilon)
        return (p.astype(jnp.float32) - step).astype(p.dtype), \
            {**s, "moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p, jnp.float32),
                "moment2": jnp.zeros_like(p, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, s, lr):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        b1p = s["beta1_pow"] * self._beta1
        b2p = s["beta2_pow"] * self._beta2
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * gf * gf
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * pf
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), \
            {**s, "moment1": m, "moment2": v, "beta1_pow": b1p,
             "beta2_pow": b2p}


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _init_state(self, p):
        return {"squared": jnp.zeros_like(p, jnp.float32),
                "linear": jnp.zeros_like(p, jnp.float32)}

    def _update(self, p, g, s, lr):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        n, z = s["squared"], s["linear"]
        new_n = n + gf * gf
        sigma = (new_n ** -self._lr_power - n ** -self._lr_power) / lr
        new_z = z + gf - sigma * pf
        denom = new_n ** -self._lr_power / lr + 2 * self._l2
        new_p = jnp.where(
            jnp.abs(new_z) > self._l1,
            (jnp.sign(new_z) * self._l1 - new_z) / denom, 0.0)
        return new_p.astype(p.dtype), {**s, "squared": new_n, "linear": new_z}


class ExponentialMovingAverage:
    """ref: fluid/optimizer.py ExponentialMovingAverage (dygraph semantics)."""

    def __init__(self, model_or_params, decay=0.999, thres_steps=None):
        from ..nn.layer import Layer

        if isinstance(model_or_params, Layer):
            self._params = model_or_params.parameters()
        else:
            self._params = list(model_or_params)
        self._decay = decay
        self._thres_steps = thres_steps
        self._shadow = {p.name: jnp.asarray(p._data) for p in self._params}
        self._backup = {}
        self._step = 0

    def update(self):
        self._step += 1
        if self._thres_steps is not None:
            # warm-up ramp only when requested (ref: EMA thres_steps)
            d = min(self._decay, (1 + self._step) / (10 + self._step))
        else:
            d = self._decay
        for p in self._params:
            self._shadow[p.name] = d * self._shadow[p.name] + \
                (1 - d) * p._data.astype(self._shadow[p.name].dtype)

    def apply(self):
        self._backup = {p.name: p._data for p in self._params}
        for p in self._params:
            p._replace(self._shadow[p.name].astype(p._data.dtype))

    def restore(self):
        for p in self._params:
            p._replace(self._backup[p.name])
        self._backup = {}


class LookAhead(Optimizer):
    """ref: fluid LookaheadOptimizer: k fast steps, then slow-weights pull."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = None
        self._steps = 0

    @property
    def _param_groups(self):
        return self.inner._param_groups

    def get_lr(self):
        return self.inner.get_lr()

    def step(self):
        if self._slow is None:
            self._slow = {p.name: jnp.asarray(p._data)
                          for p in self.inner._param_groups}
        self.inner.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in self.inner._param_groups:
                slow = self._slow[p.name] + self.alpha * (
                    p._data.astype(jnp.float32) - self._slow[p.name])
                self._slow[p.name] = slow
                p._replace(slow.astype(p._data.dtype))

    def clear_grad(self):
        self.inner.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, state):
        self.inner.set_state_dict(state)


class DecayedAdagrad(Optimizer):
    """ref: fluid/optimizer.py DecayedAdagradOptimizer:
    moment = decay * moment + (1 - decay) * g^2."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._decay = decay
        self._epsilon = epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p)}

    def _update(self, p, g, s, lr):
        m = self._decay * s["moment"] + (1 - self._decay) * g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), \
            {**s, "moment": m}


class Dpsgd(Optimizer):
    """ref: fluid/optimizer.py DpsgdOptimizer (differentially-private
    SGD): per-update gradient clip to ``clip`` then Gaussian noise with
    scale ``sigma * clip`` scaled by 1/batch_size."""

    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1.0, parameters=None, seed=0, name=None):
        super().__init__(learning_rate, parameters, None, None, name)
        self._clip = clip
        self._batch = batch_size
        self._sigma = sigma

    def _update(self, p, g, s, lr):
        from ..core import random as prandom

        if isinstance(g, jax.core.Tracer) and prandom._STATE.get("ctx") \
                is None:
            # Without a threaded key the noise would bake into the
            # compiled update as a constant — identical (cancellable)
            # noise every step, voiding the DP guarantee.
            raise RuntimeError(
                "Dpsgd under jit needs a threaded PRNG key: drive it "
                "through paddle_tpu.TrainStep / paddle_tpu.jit (which "
                "thread one per step), not a bare jax.jit")
        norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        g = g * jnp.minimum(1.0, self._clip / jnp.maximum(norm, 1e-12)) \
            .astype(g.dtype)
        noise = jax.random.normal(prandom.next_key(), g.shape,
                                  jnp.float32) * (self._sigma * self._clip)
        g = g + (noise / self._batch).astype(g.dtype)
        return p - lr * g, s


class LarsMomentum(Optimizer):
    """ref: fluid/optimizer.py LarsMomentumOptimizer: layerwise adaptive
    rate scaling — local_lr = lr * coeff * ||w|| / (||g|| + decay*||w||)."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._decay = lars_weight_decay

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update(self, p, g, s, lr):
        pf = p.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        wn = jnp.sqrt(jnp.sum(pf * pf))
        gn = jnp.sqrt(jnp.sum(gf * gf))
        local = lr * self._coeff * wn / jnp.maximum(
            gn + self._decay * wn, 1e-12)
        v = self._momentum * s["velocity"] + \
            (local * (gf + self._decay * pf)).astype(p.dtype)
        return p - v, {**s, "velocity": v}


class DGCMomentum(Momentum):
    """ref: fluid DGCMomentumOptimizer (deep gradient compression). The
    compression half is a network-transport optimization for NCCL rings;
    over ICI the gradients ride XLA all-reduce, so the TPU-native
    equivalent is plain Momentum (sparsification would only add host
    work). Kept for recipe compatibility."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, momentum, parameters=parameters,
                         use_nesterov=use_nesterov,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)


class ModelAverage:
    """ref: fluid/optimizer.py ModelAverage: accumulate parameter sums
    during training; apply() swaps in the running average over the last
    [min_average_window, max_average_window] updates."""

    def __init__(self, average_window_rate, model_or_params=None,
                 min_average_window=10000, max_average_window=10000,
                 parameters=None, name=None):
        from ..nn.layer import Layer

        src = model_or_params if model_or_params is not None else parameters
        if isinstance(src, Layer):
            self._params = src.parameters()
        else:
            self._params = list(src or [])
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self._sum = {p.name: jnp.zeros_like(p._data, jnp.float32)
                     for p in self._params}
        self._count = 0
        self._backup = {}

    def step(self):
        self._count += 1
        # restart the window past max_average_window, but never while the
        # window is still shorter than min_average_window
        restart = self._count > self.max_w and self._count > self.min_w
        for p in self._params:
            if restart:
                self._sum[p.name] = p._data.astype(jnp.float32)
            else:
                self._sum[p.name] = self._sum[p.name] + \
                    p._data.astype(jnp.float32)
        if restart:
            self._count = 1

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            raise RuntimeError(
                "ModelAverage.apply() before any step(): no accumulated "
                "window to average (parameters would be zeroed)")
        self._backup = {p.name: p._data for p in self._params}
        for p in self._params:
            p._replace((self._sum[p.name] / self._count)
                       .astype(p._data.dtype))

    def restore(self, executor=None):
        for p in self._params:
            p._replace(self._backup[p.name])
        self._backup = {}


class RecomputeOptimizer:
    """ref: fluid RecomputeOptimizer: wraps an optimizer so the listed
    checkpoint activations are rematerialized in backward. TPU-native:
    recompute is a property of the forward function (jax.checkpoint via
    framework/recompute.py), so this wrapper stores the segment spec and
    otherwise delegates."""

    def __init__(self, optimizer):
        self.inner = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, **kw):
        loss.backward()
        return []

    def apply_gradients(self, params_grads=None):
        self.inner.step()

    def minimize(self, loss, **kw):
        loss.backward()
        self.inner.step()
        return None, None

    def __getattr__(self, item):
        return getattr(self.inner, item)


class PipelineOptimizer:
    """ref: fluid PipelineOptimizer: stage-parallel training. The
    TPU-native pipeline is ``dist/pipeline.py`` (GPipe over ppermute);
    this wrapper keeps the fluid recipe shape and delegates stepping."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self.inner = optimizer
        self.num_microbatches = num_microbatches

    def minimize(self, loss, **kw):
        loss.backward()
        self.inner.step()
        return None, None

    def __getattr__(self, item):
        return getattr(self.inner, item)
