"""Gradient clipping (ref: python/paddle/fluid/clip.py).

Clippers transform a list of (param, grad) pairs. Global-norm clip computes
the norm in float32 across all grads — one fused XLA reduction per step when
run under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._apply(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _apply(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (p is not None and not getattr(p, "need_clip", True)):
                out.append((p, g))
            else:
                out.append((p, jnp.clip(g, self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _apply(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (p is not None and not getattr(p, "need_clip", True)):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _apply(self, params_grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for p, g in params_grads
              if g is not None and (p is None or getattr(p, "need_clip", True))]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or (p is not None and not getattr(p, "need_clip", True)):
                out.append((p, g))
            else:
                out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


# fluid-era aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


class ErrorClipByValue:
    """ref: fluid/clip.py ErrorClipByValue: clips the *gradient of an
    op's output* during backward. With whole-graph XLA autodiff there is
    no per-op error channel; attach this to a Tensor-producing call via
    ``apply(x)`` to clamp its gradient."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(-max if min is None else min)

    def apply(self, x):
        import jax

        @jax.custom_vjp
        def _clip_grad(v):
            return v

        def fwd(v):
            return v, None

        def bwd(_, g):
            import jax.numpy as jnp

            return (jnp.clip(g, self.min, self.max),)

        _clip_grad.defvjp(fwd, bwd)
        from ..core.tensor import Tensor
        from ..core import dispatch

        return dispatch.apply("error_clip", _clip_grad, x)


_GLOBAL_GRAD_CLIP = None


def set_gradient_clip(clip, param_list=None, program=None):
    """ref: fluid/clip.py set_gradient_clip: registers a default clip
    used by optimizers constructed without an explicit grad_clip."""
    global _GLOBAL_GRAD_CLIP
    _GLOBAL_GRAD_CLIP = clip


def get_gradient_clip():
    return _GLOBAL_GRAD_CLIP
