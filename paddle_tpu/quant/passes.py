"""1.x quantization pass classes
(ref: python/paddle/fluid/contrib/slim/quantization/quantization_pass.py
and fluid/contrib/quantize/quantize_transpiler.py).

The reference passes rewrite IrGraph/ProgramDesc: insert fake_quant /
fake_dequant ops, freeze trained scales, swap weights to int8. The
XLA-era equivalents operate on eager ``nn.Layer`` models with the
quant/ machinery (fake-quant STE wrappers, int8-resident layers), and
XLA fuses the (de)quant arithmetic — there is no separate mobile/int8
kernel set to target, so the "pass" verbs map onto model rewrites:

- QuantizationTransformPass.apply(model)  -> QAT fake-quant wrapping
- AddQuantDequantPass.apply(model)        -> same, input-quant only
- QuantizationFreezePass.apply(model)     -> QAT wrappers -> int8 layers
- ConvertToInt8Pass.apply(model)          -> weight-only int8 residency
- OutScaleForTrainingPass.apply(model)    -> abs-max output observers
- OutScaleForInferencePass.apply(model)   -> freeze observed out scales
- TransformForMobilePass                  -> no-op (no mobile kernel set)
"""
from __future__ import annotations

import numpy as np

from ..nn.layer import Layer
from . import QAT, QuantizedConv2D, QuantizedLinear, quantize_model

__all__ = [
    "QuantizationTransformPass", "QuantizationFreezePass",
    "ConvertToInt8Pass", "TransformForMobilePass",
    "OutScaleForTrainingPass", "OutScaleForInferencePass",
    "AddQuantDequantPass", "QuantizeTranspiler",
]


def _as_model(graph):
    model = getattr(graph, "_model", graph)
    if not isinstance(model, Layer):
        raise TypeError(
            "XLA-era quantization passes operate on nn.Layer models "
            f"(got {type(graph).__name__}); for saved static bundles "
            "use quant.quantize_inference_model")
    return model


class QuantizationTransformPass:
    """ref: quantization_pass.py QuantizationTransformPass — insert
    trainable fake-quant on weights (+ inputs)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", **kw):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits

    def apply(self, graph):
        model = _as_model(graph)
        QAT(bits=self._weight_bits,
            quantize_inputs=self._activation_bits > 0).quantize(model)
        return graph


class AddQuantDequantPass(QuantizationTransformPass):
    """ref: quantization_pass.py AddQuantDequantPass — quant/dequant on
    activations of additional op types; here the same fake-quant
    wrapping with input quantization on."""


class QuantizationFreezePass:
    """ref: quantization_pass.py QuantizationFreezePass — replace the
    trained fake-quant wrappers with real int8-weight layers."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max", **kw):
        self._weight_bits = weight_bits

    def apply(self, graph):
        model = _as_model(graph)
        QAT(bits=self._weight_bits).convert(model)
        return graph


class ConvertToInt8Pass:
    """ref: quantization_pass.py ConvertToInt8Pass — weight-only int8
    residency (HBM holds int8 + scale; dequant fuses into the op)."""

    def __init__(self, scope=None, place=None, quantizable_op_type=None,
                 **kw):
        pass

    def apply(self, graph):
        model = _as_model(graph)
        quantize_model(model)
        return graph


class TransformForMobilePass:
    """ref: quantization_pass.py TransformForMobilePass — renames quant
    ops for the Paddle-Lite mobile kernel set. No TPU analog: XLA is
    the only lowering target, so this is a documented no-op."""

    def __init__(self, **kw):
        pass

    def apply(self, graph):
        return graph


class OutScaleForTrainingPass:
    """ref: quantization_pass.py OutScaleForTrainingPass — observe
    per-layer output abs-max during training (forward hooks here)."""

    def __init__(self, scope=None, place=None, moving_rate=0.9, **kw):
        self._moving_rate = moving_rate
        self.out_scales = {}
        self._handles = []

    def apply(self, graph):
        model = _as_model(graph)
        for name, layer in model.named_sublayers():
            if isinstance(layer, (QuantizedLinear, QuantizedConv2D)) or \
                    type(layer).__name__ in ("Linear", "Conv2D",
                                             "QATLinear", "QATConv2D"):
                self._handles.append(layer.register_forward_post_hook(
                    self._observer(name)))
        return graph

    def _observer(self, name):
        def hook(layer, inputs, output):
            mx = float(np.abs(np.asarray(output.numpy())).max())
            prev = self.out_scales.get(name)
            self.out_scales[name] = mx if prev is None else (
                self._moving_rate * prev + (1 - self._moving_rate) * mx)
            return output

        return hook

    def remove(self):
        for h in self._handles:
            h.remove()


class OutScaleForInferencePass:
    """ref: quantization_pass.py OutScaleForInferencePass — freeze the
    observed output scales onto the model for inference consumers."""

    def __init__(self, scope=None, training_pass=None, **kw):
        self._training_pass = training_pass

    def apply(self, graph):
        model = _as_model(graph)
        if self._training_pass is not None:
            model._out_threshold = dict(self._training_pass.out_scales)
        return graph


class QuantizeTranspiler:
    """ref: contrib/quantize/quantize_transpiler.py — the pre-slim
    three-verb quantization flow over a model."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits

    def training_transpile(self, program=None, startup_program=None):
        """Fake-quant wrap for QAT (ref: training_transpile)."""
        return QuantizationTransformPass(
            weight_bits=self._weight_bits,
            activation_bits=self._activation_bits).apply(program)

    def freeze_program(self, program, place=None, fuse_bn=False,
                       scope=None):
        """Trained wrappers -> real int8 layers (ref: freeze_program)."""
        return QuantizationFreezePass(
            weight_bits=self._weight_bits).apply(program)

    def convert_to_int8(self, program, place=None, scope=None):
        """Weight-only int8 residency (ref: convert_to_int8)."""
        return ConvertToInt8Pass().apply(program)
