"""Quantization: post-training int8 (PTQ) and quant-aware training (QAT).

Ref (capability target): the reference slim quantization suite —
contrib/slim/quantization/post_training_quantization.py (calibrate →
per-tensor/per-channel scales → int8 weights) and
quantization_pass.py's fake_quantize_abs_max /
fake_quantize_moving_average_abs_max ops with straight-through gradients.

TPU-native design: weights are stored int8 + per-channel f32 scales and
dequantized right at the matmul/conv input — XLA fuses the dequant into
the op, so HBM traffic (the usual bottleneck) drops ~4x while the MXU
still runs its native precision. Fake-quant ops carry a custom_vjp
straight-through estimator so QAT works inside the fused TrainStep.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._base import register, apply, unwrap
from ..nn.layer import Layer
from ..nn.layers.common import Linear
from ..nn.layers.conv import Conv2D

__all__ = [
    "fake_quantize_abs_max", "fake_quantize_dequantize",
    "quantize_abs_max", "dequantize",
    "QuantizedLinear", "QuantizedConv2D", "QATLinear", "QATConv2D",
    "PostTrainingQuantization", "quantize_model", "QAT",
]


# ---------------------------------------------------------------------------
# fake-quant ops (STE gradients)
# ---------------------------------------------------------------------------


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fq(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fq_fwd(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    in_range = (jnp.abs(x) <= s).astype(x.dtype)
    return _fq(x, scale, qmax), (in_range, scale)


def _fq_bwd(qmax, res, g):
    # straight-through inside the clip range, zero outside
    in_range, scale = res
    return (g * in_range, jnp.zeros_like(scale))


_fq.defvjp(_fq_fwd, _fq_bwd)


@register("fake_quantize_abs_max")
def _fake_quantize_abs_max(x, *, bits, channel_axis):
    qmax = float(2 ** (bits - 1) - 1)
    if channel_axis is None:
        scale = jnp.max(jnp.abs(x))
    else:
        red = tuple(i for i in range(x.ndim) if i != channel_axis)
        shape = [1] * x.ndim
        shape[channel_axis] = -1
        scale = jnp.max(jnp.abs(x), axis=red).reshape(shape)
    return _fq(x, scale, qmax)


def fake_quantize_abs_max(x, bits=8, channel_axis=None, name=None):
    """Simulated quantization with abs-max scaling and straight-through
    gradients (ref: quantization_pass.py fake_quantize_abs_max)."""
    return apply("fake_quantize_abs_max", x, bits=int(bits),
                 channel_axis=channel_axis)


fake_quantize_dequantize = fake_quantize_abs_max


def quantize_abs_max(w, bits=8, channel_axis=None):
    """Real quantization: returns (int8 values, f32 scale) host-side."""
    arr = np.asarray(unwrap(w), np.float32)
    qmax = 2 ** (bits - 1) - 1
    if channel_axis is None:
        scale = np.maximum(np.abs(arr).max(), 1e-8)
    else:
        red = tuple(i for i in range(arr.ndim) if i != channel_axis)
        scale = np.maximum(np.abs(arr).max(axis=red, keepdims=True), 1e-8)
    q = np.clip(np.round(arr / scale * qmax), -qmax, qmax).astype(np.int8)
    return q, (scale / qmax).astype(np.float32)


def dequantize(q, scale, dtype=jnp.float32):
    return jnp.asarray(q, dtype) * jnp.asarray(scale, dtype)


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------


class QuantizedLinear(Layer):
    """Linear with int8 weight storage + per-output-channel scales; the
    dequant sits right before the matmul so XLA fuses it (weight HBM
    reads shrink 4x)."""

    def __init__(self, linear, bits=8):
        super().__init__()
        q, s = quantize_abs_max(linear.weight, bits=bits, channel_axis=1)
        self.register_buffer("qweight", Tensor(jnp.asarray(q),
                                               _internal=True))
        self.register_buffer("wscale", Tensor(jnp.asarray(s),
                                              _internal=True))
        self.bias = linear.bias
        self._dtype = unwrap(linear.weight).dtype

    def forward(self, x):
        from ..nn import functional as F

        w = Tensor(dequantize(self.qweight._data, self.wscale._data,
                              self._dtype), _internal=True)
        return F.linear(x, w, self.bias)


class QuantizedConv2D(Layer):
    """Conv2D with int8 weights (per-out-channel scales on axis 0)."""

    def __init__(self, conv, bits=8):
        super().__init__()
        q, s = quantize_abs_max(conv.weight, bits=bits, channel_axis=0)
        self.register_buffer("qweight", Tensor(jnp.asarray(q),
                                               _internal=True))
        self.register_buffer("wscale", Tensor(jnp.asarray(s),
                                              _internal=True))
        self.bias = conv.bias
        self._dtype = unwrap(conv.weight).dtype
        self._cfg = dict(stride=conv._stride, padding=conv._padding,
                         dilation=conv._dilation, groups=conv._groups)

    def forward(self, x):
        from ..nn import functional as F

        w = Tensor(dequantize(self.qweight._data, self.wscale._data,
                              self._dtype), _internal=True)
        return F.conv2d(x, w, self.bias, **self._cfg)


def quantize_model(model, bits=8, quantizable=(Linear, Conv2D)):
    """Swap every Linear/Conv2D in-place for its int8 twin; returns the
    model (weight-only PTQ — the core of the reference's PTQ pipeline)."""
    for name, child in list(model.named_children()):
        if isinstance(child, Linear) and Linear in quantizable:
            setattr(model, name, QuantizedLinear(child, bits=bits))
        elif isinstance(child, Conv2D) and Conv2D in quantizable:
            setattr(model, name, QuantizedConv2D(child, bits=bits))
        else:
            quantize_model(child, bits=bits, quantizable=quantizable)
    return model


class PostTrainingQuantization:
    """ref: post_training_quantization.py — calibrate activation ranges
    on sample data, then emit the quantized model.

    >>> ptq = PostTrainingQuantization(model, loader, algo="abs_max")
    >>> qmodel = ptq.quantize()

    Weight quantization is exact (per-channel abs-max); activation scales
    are collected per quantizable layer during calibration and stored on
    the layer (``act_scale``) for serving-side use.
    """

    def __init__(self, model, data_loader=None, batch_nums=4,
                 algo="abs_max", bits=8,
                 quantizable_op_type=("mul", "conv2d")):
        if algo not in ("abs_max", "avg"):
            raise NotImplementedError(
                f"algo={algo!r} not implemented (have 'abs_max', 'avg'; "
                "the reference's KL/hist/mse calibrators are not)")
        self.model = model
        self.loader = data_loader
        self.batch_nums = batch_nums
        self.algo = algo
        self.bits = bits
        self._acts = {}

    def _hook(self, name):
        def fn(layer, inputs, output):
            x = np.asarray(unwrap(inputs[0]))
            peak = float(np.abs(x).max())
            if self.algo == "avg":
                self._acts.setdefault(name, []).append(peak)
            else:
                self._acts[name] = max(self._acts.get(name, 0.0), peak)
            return None

        return fn

    def quantize(self):
        handles = []
        targets = [(n, l) for n, l in self.model.named_sublayers()
                   if isinstance(l, (Linear, Conv2D))]
        if self.loader is not None:
            for n, l in targets:
                handles.append(l.register_forward_post_hook(self._hook(n)))
            self.model.eval()
            for i, batch in enumerate(self.loader):
                if i >= self.batch_nums:
                    break
                xs = batch[0] if isinstance(batch, (list, tuple)) else batch
                self.model(xs if isinstance(xs, Tensor)
                           else Tensor(jnp.asarray(np.asarray(xs)),
                                       _internal=True))
            for h in handles:
                h.remove()
        quantize_model(self.model, bits=self.bits)
        # attach calibrated activation scales to the swapped-in layers
        for n, l in self.model.named_sublayers():
            if isinstance(l, (QuantizedLinear, QuantizedConv2D)):
                peak = self._acts.get(n)
                if isinstance(peak, list):
                    peak = float(np.mean(peak))
                if peak is not None:
                    l.act_scale = peak / (2 ** (self.bits - 1) - 1)
        return self.model


class QATLinear(Layer):
    """Fake-quant wrapper owning the original Linear (same Parameter
    objects, so optimizers built after wrapping train the fp32 master
    weights; gradients flow straight-through the fake-quant)."""

    def __init__(self, linear, bits=8, quantize_inputs=True):
        super().__init__()
        self.inner = linear
        self.bits = bits
        self.quantize_inputs = quantize_inputs

    def forward(self, x):
        from ..nn import functional as F

        if self.quantize_inputs:
            x = fake_quantize_abs_max(x, bits=self.bits)
        w = fake_quantize_abs_max(self.inner.weight, bits=self.bits,
                                  channel_axis=1)
        return F.linear(x, w, self.inner.bias)


class QATConv2D(Layer):
    def __init__(self, conv, bits=8, quantize_inputs=True):
        super().__init__()
        self.inner = conv
        self.bits = bits
        self.quantize_inputs = quantize_inputs

    def forward(self, x):
        from ..nn import functional as F

        if self.quantize_inputs:
            x = fake_quantize_abs_max(x, bits=self.bits)
        w = fake_quantize_abs_max(self.inner.weight, bits=self.bits,
                                  channel_axis=0)
        c = self.inner
        return F.conv2d(x, w, c.bias, stride=c._stride,
                        padding=c._padding, dilation=c._dilation,
                        groups=c._groups)


class QAT:
    """Quant-aware training (ref: quantization_pass.py QAT transform):
    swap Linear/Conv2D for fake-quant wrappers, train as usual (build
    the optimizer AFTER quantize()), then convert to real int8 layers.

    >>> qat = QAT(bits=8); qat.quantize(model)   # train as usual
    >>> qat.convert(model)                       # -> real int8 layers
    """

    def __init__(self, bits=8, quantize_inputs=True):
        self.bits = bits
        self.quantize_inputs = quantize_inputs

    def quantize(self, model):
        for name, child in list(model.named_children()):
            if isinstance(child, Linear):
                setattr(model, name, QATLinear(child, self.bits,
                                               self.quantize_inputs))
            elif isinstance(child, Conv2D):
                setattr(model, name, QATConv2D(child, self.bits,
                                               self.quantize_inputs))
            else:
                self.quantize(child)
        return model

    def convert(self, model):
        for name, child in list(model.named_children()):
            if isinstance(child, QATLinear):
                setattr(model, name,
                        QuantizedLinear(child.inner, bits=self.bits))
            elif isinstance(child, QATConv2D):
                setattr(model, name,
                        QuantizedConv2D(child.inner, bits=self.bits))
            else:
                self.convert(child)
        return model


# ---------------------------------------------------------------------------
# static inference-bundle quantization (save -> PTQ pass -> Predictor)
# ---------------------------------------------------------------------------

# weight-consuming op types and the output-channel axis of their weight
# operand (input slot 1); ref quantization_pass.py _weight_quantize_type
_QUANT_OPS = {
    "conv2d": 0,          # weight (out, in, kh, kw)
    "linear": 1,          # weight (in, out)
    "linear_nobias": 1,
    "matmul": 1,
}


def quantize_inference_model(path_prefix, out_prefix=None, bits=8,
                             min_elems=512, quantizable_op_type=None):
    """Post-training int8 pass over a ``save_inference_model`` bundle
    (ref: post_training_quantization.py:60 + the freeze pass in
    quantization_pass.py:703 — there a Program rewrite inserting
    quant/dequant ops; here the pass rewrites the saved bundle).

    Weights feeding matmul-like/conv ops are stored int8 with
    per-output-channel scales; ``load_inference_model`` rebuilds them as
    int8 persistables plus a prepended ``dequantize_weight`` op, so the
    Predictor keeps the int8 copy resident in HBM and XLA fuses the
    dequant into the consumer (4x weight-memory traffic cut, the right
    int8 trade on TPU where the MXU natively runs bf16).

    Weights also consumed by non-quantizable ops, smaller than
    ``min_elems``, or not floating-point are kept fp32. Returns the list
    of quantized weight names. ``out_prefix`` defaults to
    ``path_prefix + "_int8"``.
    """
    import os
    import pickle

    op_types = dict(_QUANT_OPS)
    if quantizable_op_type is not None:
        op_types = {k: v for k, v in op_types.items()
                    if k in set(quantizable_op_type)}
    out_prefix = out_prefix or (path_prefix + "_int8")
    with open(path_prefix + ".pdmodel", "rb") as f:
        desc = pickle.load(f)
    params_path = (path_prefix + ".pdiparams.npz"
                   if os.path.exists(path_prefix + ".pdiparams.npz")
                   else path_prefix + ".pdiparams")
    data = np.load(params_path, allow_pickle=True)
    if any(k.startswith("q!") for k in data.files):
        raise ValueError(
            f"{path_prefix!r} is already an int8 bundle (contains q!/s! "
            "entries); quantize the original fp32 bundle instead")
    weights = {k[2:]: data[k] for k in data.files if k.startswith("w!")}
    consts = {k[2:]: data[k] for k in data.files if k.startswith("c!")}

    # role scan: weight name -> channel axis; conflicted/other-use -> None
    roles: dict = {}
    for type_, in_names, out_names, attrs in desc["ops"]:
        axis = op_types.get(type_)
        for slot, name in enumerate(in_names):
            if name not in weights:
                continue
            if axis is not None and slot == 1:
                roles[name] = axis if roles.get(name, axis) == axis else None
            else:
                roles[name] = None  # consumed elsewhere: keep exact

    quantized = []
    out_arrays = {}
    for name, arr in weights.items():
        axis = roles.get(name)
        if (axis is None or arr.size < min_elems or arr.ndim < 2
                or not np.issubdtype(arr.dtype, np.floating)):
            out_arrays["w!" + name] = arr
            continue
        q, s = quantize_abs_max(arr, bits=bits, channel_axis=axis)
        out_arrays["q!" + name] = q
        out_arrays["s!" + name] = s.astype(np.float32)
        quantized.append(name)

    desc = dict(desc)
    desc["quant"] = {"bits": bits, "weights": sorted(quantized)}
    d = os.path.dirname(out_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_prefix + ".pdmodel", "wb") as f:
        pickle.dump(desc, f, protocol=4)
    np.savez(out_prefix + ".pdiparams",
             __consts__=np.array(list(consts)),
             **{("c!" + k): v for k, v in consts.items()},
             **out_arrays)
    return quantized


__all__ += ["quantize_inference_model"]
