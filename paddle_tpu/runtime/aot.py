"""AOT compile-and-ship: a content-addressed on-disk executable cache.

Every compile site in the framework — ``Executor._build`` (static path,
fused ``steps=K`` and plan-carrying entries included), ``TrainStep``
(eager path), the inference ``Predictor``, and ``ServeEngine``'s
prefill/decode buckets — pays a full XLA compile on a process's first
request. For a serving replica, an elastic relaunch, or a fleet
``verify_plan`` probe that compile IS the cold-start latency: it caps
autoscaling speed, and every replica pays it again for the same program.
This module is the full-program compile-once-run-anywhere stance of the
Julia-to-TPU work (PAPERS.md, arXiv 1810.09868) applied to the whole
framework: serialize the compiled executable ONCE, hydrate it from disk
everywhere else.

Design:

- **Key = content, never identity.** The cache key is a SHA-256 over the
  environment fingerprint (jax/jaxlib versions, backend platform, device
  kinds + count, ``XLA_FLAGS``, the relevant ``PADDLE_TPU_*`` knobs),
  the site kind, and the full StableHLO text of the *lowered* module —
  which already bakes in shapes, dtypes, shardings, donation
  (``jax.buffer_donor`` arg attributes), optimization level (the
  analysis passes rewrote the ops before tracing), fused step count (the
  scan is in the module), and program constants. Any ``CacheKey`` drift
  — a changed feed shape, plan, comm layout, or steps=K — produces a
  different module and therefore a clean MISS; a stale hit is
  structurally impossible, not merely checked for.
- **Fingerprint verified twice.** The fingerprint participates in the
  digest AND is stored verbatim in the envelope and re-compared at load:
  deserializing an executable produced by a different jaxlib can
  crash rather than error, so a mismatched envelope is rejected before
  any bytes reach ``deserialize_and_load`` (journaled as an ``aot``
  event with the reason).
- **Bitwise-identical by construction.** A hit deserializes the exact
  executable a local ``lowered.compile()`` would have produced (same
  module, same compile options), so outputs are bitwise identical and
  ``input_output_alias`` donation survives the round-trip —
  ``tools/perf_gate.donation_stats`` reads it straight off the hydrated
  executable.
- **Opt-in and fail-open.** With no cache configured every site keeps
  today's lazy ``jax.jit`` behavior. Any AOT failure (serialization
  unsupported, torn file, tampered envelope) falls back to an in-process
  compile and journals why — the cache can make a run faster, never
  break it.

Activation: ``configure(dir)`` (process-wide), env
``PADDLE_TPU_AOT_CACHE=dir``, ``paddle_tpu.set_compilation_cache(dir)``
(which also enables jax's native persistent cache), or per-instance
``ServeEngine(..., aot_cache_dir=...)`` / ``Config.aot_cache_dir``.
``tools/aot_cache.py`` lists/verifies/evicts entries and runs warmup
probes from a saved inference model.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import threading
import time

from ..obs import lockdep as _lockdep

__all__ = [
    "AOTCache", "configure", "configured", "active_cache",
    "resolve_cache", "fingerprint", "fingerprint_digest",
    "load_or_compile", "cache_stats", "warm_inference_model",
    "shared_cache_env", "ENV_DIR", "FORMAT_VERSION",
]

ENV_DIR = "PADDLE_TPU_AOT_CACHE"
FORMAT_VERSION = 1
_SUFFIX = ".aot"
_MAGIC = b"PTAOT1\n"

# PADDLE_TPU_* knobs that change what gets COMPILED (not just how a run
# behaves). OPT_LEVEL rewrites the op list before tracing — it is
# already visible in the module text, but keeping it here makes the
# fingerprint self-describing in `aot_cache.py --list` output.
_FINGERPRINT_KNOBS = ("PADDLE_TPU_OPT_LEVEL",)

_DISABLED = object()      # configure-level mask over the env fallback
_ACTIVE = [None]          # configure()'d cache, None (defer to env),
                          # or _DISABLED (force-off, env masked too)
_BY_DIR = {}              # dir -> AOTCache (per-instance caches share)
_LOCK = _lockdep.lock("aot.registry")


def fingerprint():
    """Everything OUTSIDE the lowered module that the executable bytes
    depend on. Touches ``jax.devices()`` — call at compile time only
    (the backend exists there); never from import paths."""
    import jax
    import jaxlib

    devs = jax.devices()
    return {
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "device_count": len(devs),
        "device_kinds": sorted({str(d.device_kind) for d in devs}),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "knobs": {k: os.environ.get(k, "") for k in _FINGERPRINT_KNOBS},
    }


def fingerprint_digest(fp=None):
    fp = fp if fp is not None else fingerprint()
    return hashlib.sha256(
        repr(sorted(fp.items())).encode()).hexdigest()


def _journal_event(**fields):
    """One ``aot`` journal event; inert without an active journal (the
    standard ``if ACTIVE`` hook pattern)."""
    try:
        from ..obs import journal as _journal

        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event("aot", **fields)
    except Exception:
        pass


# -- entry file format --------------------------------------------------------
# <digest>.aot = MAGIC | u64 header_len | JSON header | trees | payload
#
# The header (fingerprint verbatim, digest, kind/label, meta, section
# lengths) is plain JSON so verification and listing NEVER unpickle
# untrusted bytes: a tampered or foreign file is rejected on the header
# alone, and only a fingerprint-verified entry has its (pickled)
# treedefs and serialized-executable payload read at all. Writes are
# atomic (tmp + rename) so a killed writer leaves no torn entry.


def _write_entry(path, header, trees, payload):
    hjson = json.dumps(header, sort_keys=True, default=str).encode()
    # tmp name unique per process AND thread: two threads racing the
    # same digest must not interleave writes into one tmp file (the
    # os.replace of interleaved bytes would publish a torn envelope
    # under a valid digest name)
    tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack(">Q", len(hjson)))
            f.write(hjson)
            f.write(trees)
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _read_header(f):
    """Parse MAGIC + JSON header from an open entry file, leaving the
    position at the trees section. Raises ValueError on a file that is
    not (or no longer) an AOT envelope."""
    if f.read(len(_MAGIC)) != _MAGIC:
        raise ValueError("not an AOT envelope")
    (hlen,) = struct.unpack(">Q", f.read(8))
    if hlen > 1 << 24:  # a sane header is KBs; refuse absurd lengths
        raise ValueError("oversized header")
    header = json.loads(f.read(hlen))
    if not isinstance(header, dict):
        raise ValueError("header is not an object")
    return header


def _read_entry(path, want_body=True):
    """(header, trees, payload); the latter two ``None`` when
    ``want_body`` is False (listing/verify read metadata only)."""
    with open(path, "rb") as f:
        header = _read_header(f)
        if not want_body:
            return header, None, None
        trees = f.read(int(header["trees_len"]))
        payload = f.read(int(header["payload_len"]))
        if len(trees) != int(header["trees_len"]) or \
                len(payload) != int(header["payload_len"]):
            raise ValueError("truncated entry")
    return header, trees, payload


class AOTCache:
    """One on-disk cache directory of serialized executables.

    Entry file = ``<digest>.aot``: a JSON header holding the
    fingerprint (verbatim, re-verified at load), the site kind/label,
    and meta (original compile_ms, creation time), followed by the
    pickled in/out pytree defs and the serialized executable payload
    (``jax.experimental.serialize_executable``)."""

    def __init__(self, directory):
        self.dir = os.path.abspath(str(directory))
        os.makedirs(self.dir, exist_ok=True)
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.rejects = 0   # present-but-refused entries (stale/torn)
        self._lock = _lockdep.lock("aot.cache")

    # -- keys -----------------------------------------------------------------
    def key_for(self, lowered, kind, extra=""):
        """Content digest for one lowered computation: fingerprint +
        site kind + the full StableHLO module text. ``extra`` folds in
        anything the module can't see (none needed today; kept for
        forward compatibility)."""
        h = hashlib.sha256()
        h.update(fingerprint_digest().encode())
        h.update(b"\x00" + str(kind).encode())
        h.update(b"\x00" + repr(extra).encode())
        h.update(b"\x00" + lowered.as_text().encode())
        return h.hexdigest()

    def _path(self, digest):
        return os.path.join(self.dir, digest + _SUFFIX)

    # -- load -----------------------------------------------------------------
    def load(self, digest):
        """(Compiled, meta) on a verified hit; (None, reason) otherwise.
        A present-but-wrong entry NEVER reaches a deserializer — pickle
        included: the JSON header's stored fingerprint and digest must
        match the live ones before the treedef/payload bytes are even
        read (defends against env drift the digest didn't cover — and
        against a tampered or hash-collided file)."""
        path = self._path(digest)
        if not os.path.exists(path):
            with self._lock:
                self.misses += 1
            return None, "miss"
        try:
            with open(path, "rb") as f:
                header = _read_header(f)
                reason = self._verify_header(header, digest)
                if reason is None:
                    trees = f.read(int(header["trees_len"]))
                    payload = f.read(int(header["payload_len"]))
                    if len(trees) != int(header["trees_len"]) or \
                            len(payload) != int(header["payload_len"]):
                        reason = "truncated entry"
        except Exception as e:
            reason = f"unreadable envelope ({type(e).__name__})"
        if reason is not None:
            with self._lock:
                self.rejects += 1
            _journal_event(action="reject", digest=digest, reason=reason)
            return None, reason
        try:
            from jax.experimental import serialize_executable as _se

            in_tree, out_tree = pickle.loads(trees)
            exe = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            with self._lock:
                self.rejects += 1
            _journal_event(action="reject", digest=digest,
                           reason=f"deserialize failed: "
                                  f"{type(e).__name__}")
            return None, f"deserialize failed ({type(e).__name__})"
        with self._lock:
            self.hits += 1
        return exe, header.get("meta", {})

    def _verify_header(self, header, digest=None, live=None):
        """None when the entry header is trustworthy, else the refusal
        reason. ``live`` lets batch callers (verify()) compute the
        live fingerprint once instead of per entry."""
        if header.get("format") != FORMAT_VERSION:
            return f"format {header.get('format')} != {FORMAT_VERSION}"
        if digest is not None and header.get("digest") != digest:
            return "digest mismatch (renamed or tampered entry)"
        live = live if live is not None else fingerprint()
        stored = header.get("fingerprint")
        if stored != live:
            drift = sorted(k for k in set(live) | set(stored or {})
                           if (stored or {}).get(k) != live.get(k))
            return f"fingerprint drift: {drift}"
        for k in ("trees_len", "payload_len"):
            if not isinstance(header.get(k), int) or header[k] <= 0:
                return f"missing {k}"
        return None

    # -- store ----------------------------------------------------------------
    def store(self, digest, exe, kind, label=None, meta=None):
        """Serialize + atomically publish one compiled executable.
        Returns True on publish; False (journaled) when the backend
        can't serialize this executable — the run continues on the
        in-process compile either way."""
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(exe)
        except Exception as e:
            _journal_event(action="store_failed", digest=digest,
                           reason=f"serialize: {type(e).__name__}")
            return False
        trees = pickle.dumps((in_tree, out_tree), protocol=4)
        payload = bytes(payload)
        header = {
            "format": FORMAT_VERSION,
            "digest": digest,
            "fingerprint": fingerprint(),
            "kind": str(kind),
            "label": label,
            "meta": dict(meta or {}, created=time.time()),
            "trees_len": len(trees),
            "payload_len": len(payload),
        }
        try:
            _write_entry(self._path(digest), header, trees, payload)
        except Exception as e:
            _journal_event(action="store_failed", digest=digest,
                           reason=f"write: {type(e).__name__}")
            return False
        with self._lock:
            self.stores += 1
        return True

    # -- introspection (tools/aot_cache.py) -----------------------------------
    def entries(self):
        """Metadata of every entry from the JSON header alone — the
        (possibly multi-MB) executable payload is never read: digest,
        kind/label, bytes on disk, age, fingerprint summary, original
        compile_ms. Unreadable files are listed with an ``error`` field
        instead of being skipped silently."""
        out = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.dir, name)
            rec = {"digest": name[:-len(_SUFFIX)],
                   "bytes": os.path.getsize(path),
                   "age_s": max(0.0, time.time() - os.path.getmtime(path))}
            try:
                header, _, _ = _read_entry(path, want_body=False)
                rec.update({
                    "kind": header.get("kind"),
                    "label": header.get("label"),
                    "compile_ms": (header.get("meta") or {}).get(
                        "compile_ms"),
                    "jax": (header.get("fingerprint") or {}).get("jax"),
                    "platform": (header.get("fingerprint") or {}).get(
                        "platform"),
                })
            except Exception as e:
                rec["error"] = f"{type(e).__name__}"
            out.append(rec)
        return out

    def verify(self):
        """Re-check every entry's header against the live fingerprint
        (headers only — no payload read, nothing unpickled). Returns
        (ok, stale) digest lists — stale entries would refuse to load,
        so ``--evict --stale`` can clear them."""
        ok, stale = [], []
        live = fingerprint()  # once, not per entry (jax.devices())
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(_SUFFIX):
                continue
            digest = name[:-len(_SUFFIX)]
            try:
                header, _, _ = _read_entry(
                    os.path.join(self.dir, name), want_body=False)
                reason = self._verify_header(header, digest, live=live)
            except Exception:
                reason = "unreadable"
            (ok if reason is None else stale).append(digest)
        return ok, stale

    def evict(self, digests=None, older_than_s=None, stale_only=False):
        """Remove entries: an explicit digest list, everything older
        than ``older_than_s``, only fingerprint-stale ones, or (no
        filter) the whole cache. Returns the number removed."""
        if stale_only:
            _, digests = self.verify()
        removed = 0
        for name in list(os.listdir(self.dir)):
            if not name.endswith(_SUFFIX):
                continue
            digest = name[:-len(_SUFFIX)]
            path = os.path.join(self.dir, name)
            if digests is not None and digest not in digests:
                continue
            if older_than_s is not None and \
                    time.time() - os.path.getmtime(path) < older_than_s:
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        return removed

    def stats(self):
        return {"hits": self.hits, "misses": self.misses,
                "stores": self.stores, "rejects": self.rejects,
                "entries": sum(1 for n in os.listdir(self.dir)
                               if n.endswith(_SUFFIX)),
                "dir": self.dir}


# -- process-wide activation --------------------------------------------------


def configure(directory):
    """Activate the process-wide AOT cache. Accepts a directory, an
    ``AOTCache``, or a previous ``configured()`` snapshot to restore
    (including the disabled sentinel). ``None`` clears the explicit
    setting — the env ``PADDLE_TPU_AOT_CACHE`` fallback applies again;
    use ``disable()`` to force-off an env-activated cache too. Returns
    the AOTCache (or None). ``set_compilation_cache`` routes here so
    one call persists BOTH jax's native compilation cache and the
    framework's executable envelopes."""
    if directory is None or directory is _DISABLED:
        _ACTIVE[0] = directory
        return None
    _ACTIVE[0] = directory if isinstance(directory, AOTCache) \
        else _cache_at(directory)
    return _ACTIVE[0]


def disable():
    """Force the AOT cache OFF for this process, masking the env
    ``PADDLE_TPU_AOT_CACHE`` fallback as well — the programmatic off
    switch ``set_compilation_cache(None)`` promises. Undo with
    ``configure(dir)`` or ``configure(None)`` (the latter re-enables
    the env fallback)."""
    _ACTIVE[0] = _DISABLED


def configured():
    """The explicit configure()/disable() state (None when only the
    env var — or nothing — is active): snapshot this before a
    temporary ``configure()`` and pass it back to restore."""
    return _ACTIVE[0]


def _cache_at(directory):
    d = os.path.abspath(str(directory))
    with _LOCK:
        c = _BY_DIR.get(d)
        if c is None:
            c = _BY_DIR[d] = AOTCache(d)
    return c


def active_cache():
    """The cache compile sites should consult: an explicit
    ``configure()``/``disable()`` wins; otherwise env
    ``PADDLE_TPU_AOT_CACHE`` (re-read per call — a subprocess gets it
    from its environment with no Python-side setup); else None
    (lazy-jit behavior everywhere)."""
    a = _ACTIVE[0]
    if a is _DISABLED:
        return None
    if a is not None:
        return a
    d = os.environ.get(ENV_DIR, "")
    return _cache_at(d) if d else None


def resolve_cache(directory=None):
    """Per-instance override hook (``ServeEngine(aot_cache_dir=...)``,
    ``Config.aot_cache_dir``): an explicit directory wins, else the
    process-wide active cache."""
    if directory is not None:
        return _cache_at(directory)
    return active_cache()


def cache_stats():
    """Stats of the active process-wide cache, or None."""
    c = active_cache()
    return c.stats() if c is not None else None


def shared_cache_env(directory):
    """The env block that hands a SHARED executable cache to a fleet of
    worker processes (``serving.fleet.ReplicaPool``): creates the
    directory and returns ``{ENV_DIR: abspath}``. Concurrent workers
    compiling the same digest race only on the atomic tmp+rename
    publish (last writer wins, both envelopes identical), so the first
    incarnation of every replica can warm the cache in parallel and
    every relaunch/scale-up after that hydrates instead of compiling."""
    d = os.path.abspath(str(directory))
    os.makedirs(d, exist_ok=True)
    return {ENV_DIR: d}


# -- the one compile-site flow ------------------------------------------------


def load_or_compile(jit_fn, args, kind, cache=None, label=None):
    """The whole AOT flow for one compile site: trace (cheap), hash the
    module, hydrate from disk or compile + publish.

    Returns ``(compiled, info)`` where ``compiled`` is a
    ``jax.stages.Compiled`` callable with the SAME calling convention
    as ``jit_fn`` (donation and shardings baked in), or ``(None,
    info)`` when anything failed — the caller then keeps its lazy
    ``jit_fn`` untouched. ``info``:

    - ``source``: ``"aot_disk"`` (hydrated) or ``"xla"`` (compiled
      here; published unless ``stored`` is False)
    - ``deserialize_ms`` / ``compile_ms_avoided`` on a hit
    - ``xla_compile_ms`` on a miss (genuine XLA wall time — unlike the
      lazy path's trace-side ``compile_ms``)
    - ``digest``, ``miss_reason``
    """
    cache = cache if cache is not None else active_cache()
    if cache is None:
        return None, None
    try:
        import jax

        lowered = jit_fn.lower(*args)
        # the input treedef joins the digest: pytree METADATA (e.g. a
        # TrainStep's opt-state dict keyed by param names) is part of
        # the serialized calling convention but invisible in the
        # module text — two builds with identical StableHLO and
        # different dict keys must not share an entry
        digest = cache.key_for(
            lowered, kind,
            extra=str(jax.tree_util.tree_structure(args)))
    except Exception as e:
        _journal_event(action="lower_failed", kind=kind,
                       reason=type(e).__name__)
        return None, {"source": None, "error": type(e).__name__}
    # timed from here: deserialize_ms is the cost of READING the cache
    # (disk + deserialize), not the trace/hash above — both paths pay
    # those identically
    t0 = time.perf_counter()
    exe, meta = cache.load(digest)
    if exe is not None:
        info = {"source": "aot_disk", "digest": digest,
                "deserialize_ms": (time.perf_counter() - t0) * 1e3,
                "compile_ms_avoided": (meta or {}).get("compile_ms")}
        _journal_event(action="hit", kind=kind, digest=digest,
                       deserialize_ms=info["deserialize_ms"],
                       compile_ms_avoided=info["compile_ms_avoided"])
        return exe, info
    miss_reason = meta  # load() returns the refusal/miss reason here
    try:
        t1 = time.perf_counter()
        exe = lowered.compile()
        xla_ms = (time.perf_counter() - t1) * 1e3
    except Exception as e:
        _journal_event(action="compile_failed", kind=kind,
                       digest=digest, reason=type(e).__name__)
        return None, {"source": None, "error": type(e).__name__,
                      "digest": digest}
    stored = cache.store(digest, exe, kind, label=label,
                         meta={"compile_ms": xla_ms})
    return exe, {"source": "xla", "digest": digest,
                 "xla_compile_ms": xla_ms, "stored": stored,
                 "miss_reason": miss_reason}


def provenance_fields(info):
    """The journal `compile`-event provenance fields for one
    ``load_or_compile`` info dict: ``via`` ("xla" | "aot_disk") plus
    ``deserialize_ms``/``compile_ms_avoided`` on a hit or
    ``xla_compile_ms`` on a miss. Empty dict for ``info=None`` (AOT
    inactive) so call sites can splat it unconditionally."""
    if not info or not info.get("source"):
        return {}
    prov = info["source"]
    out = {"via": prov}
    if prov == "aot_disk":
        out["deserialize_ms"] = info.get("deserialize_ms")
        if info.get("compile_ms_avoided") is not None:
            out["compile_ms_avoided"] = info["compile_ms_avoided"]
    elif info.get("xla_compile_ms") is not None:
        out["xla_compile_ms"] = info["xla_compile_ms"]
    return out


# -- warmup ------------------------------------------------------------------


def warm_inference_model(path_prefix, buckets=(1,), cache=None):
    """Warm the executable cache from a SAVED inference model: load it
    through the real ``Predictor`` (the exact code path a serving
    replica runs) and drive one zeroed batch per bucket size, so the
    replica's first real request hydrates instead of compiling.
    Returns the number of entries warmed. Feed shapes come from the
    saved program; dynamic non-batch dims make a feed unwarmable (it
    is skipped with a journal event, not an error)."""
    import numpy as np

    from ..inference.predictor import Config, Predictor

    cfg = Config(str(path_prefix))
    if cache is not None:
        cfg.aot_cache_dir = cache.dir if isinstance(cache, AOTCache) \
            else str(cache)
    pred = Predictor(cfg)
    blk = pred._program.global_block
    warmed = 0
    for b in buckets:
        feed = {}
        ok = True
        for name in pred.get_input_names():
            v = blk.vars.get(name)
            if v is None:
                ok = False
                break
            dyn = set(getattr(v, "dynamic_dims", ()) or ())
            if any(d != 0 for d in dyn):
                ok = False  # dynamic non-batch dim: nothing to pad to
                break
            shape = [int(s) for s in v.shape]
            if shape:
                shape[0] = int(b)  # batch dim follows the bucket
            feed[name] = np.zeros(tuple(shape), np.dtype(v._data.dtype))
        if not ok:
            _journal_event(action="warm_skipped", prefix=str(path_prefix),
                           bucket=int(b), reason="dynamic feed dims")
            continue
        try:
            pred.run(feed)
            warmed += 1
        except Exception as e:
            _journal_event(action="warm_failed", prefix=str(path_prefix),
                           bucket=int(b), reason=type(e).__name__)
    return warmed
