// paddle_tpu host runtime: the native layer under the Python data pipeline.
//
// TPU-native replacement for the reference's C++ runtime pieces that still
// matter off-device:
//   - paddle/fluid/memory/allocation/* (arena/pool host allocator w/ stats)
//   - paddle/fluid/operators/reader/buffered_reader.cc (double-buffer
//     prefetch)  -> blocking MPMC ring buffer feeding DataLoader
//   - paddle/fluid/framework/io (record file shards) -> length-prefixed
//     record shard writer/reader with CRC and threaded readahead
//
// Device memory itself belongs to XLA/PJRT on TPU; this runtime owns the
// HOST side: staging buffers, pipeline queues, shard IO. Exposed as a C ABI
// for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread -o libptruntime.so
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Arena allocator with stats (host staging memory)
// ---------------------------------------------------------------------------

struct PtArena {
  std::mutex mu;
  size_t block_size;
  std::vector<void*> blocks;     // owned big blocks
  char* cur = nullptr;           // bump pointer inside current block
  size_t cur_left = 0;
  // free-list pooling for large one-off allocations
  std::deque<std::pair<void*, size_t>> pool;
  // stats
  std::atomic<uint64_t> total_allocated{0};
  std::atomic<uint64_t> in_use{0};
  std::atomic<uint64_t> peak{0};
  std::atomic<uint64_t> alloc_count{0};
};

PtArena* pt_arena_new(size_t block_size) {
  auto* a = new PtArena();
  a->block_size = block_size ? block_size : (1u << 20);
  return a;
}

static void pt_bump_stats(PtArena* a, size_t n) {
  a->alloc_count.fetch_add(1, std::memory_order_relaxed);
  uint64_t now = a->in_use.fetch_add(n, std::memory_order_relaxed) + n;
  uint64_t peak = a->peak.load(std::memory_order_relaxed);
  while (now > peak &&
         !a->peak.compare_exchange_weak(peak, now, std::memory_order_relaxed)) {
  }
}

void* pt_arena_alloc(PtArena* a, size_t n) {
  if (!a || n == 0) return nullptr;
  n = (n + 63) & ~size_t(63);  // 64B-align: friendly to memcpy/SIMD
  std::lock_guard<std::mutex> lk(a->mu);
  if (n >= a->block_size / 2) {
    // large: serve from pool if a fitting blob exists (first fit)
    for (auto it = a->pool.begin(); it != a->pool.end(); ++it) {
      if (it->second >= n && it->second <= n * 2) {
        void* p = it->first;
        a->pool.erase(it);
        pt_bump_stats(a, n);
        return p;
      }
    }
    void* p = ::operator new(n, std::nothrow);
    if (!p) return nullptr;
    a->total_allocated.fetch_add(n, std::memory_order_relaxed);
    pt_bump_stats(a, n);
    a->blocks.push_back(p);  // owned; freed at arena destroy
    return p;
  }
  if (a->cur_left < n) {
    char* blk = static_cast<char*>(::operator new(a->block_size, std::nothrow));
    if (!blk) return nullptr;
    a->blocks.push_back(blk);
    a->total_allocated.fetch_add(a->block_size, std::memory_order_relaxed);
    a->cur = blk;
    a->cur_left = a->block_size;
  }
  void* p = a->cur;
  a->cur += n;
  a->cur_left -= n;
  pt_bump_stats(a, n);
  return p;
}

void pt_arena_reset(PtArena* a) {
  // bulk free: keep the first block, drop the rest (epoch-style reuse)
  std::lock_guard<std::mutex> lk(a->mu);
  for (size_t i = 1; i < a->blocks.size(); ++i) ::operator delete(a->blocks[i]);
  if (!a->blocks.empty()) {
    a->blocks.resize(1);
    a->cur = static_cast<char*>(a->blocks[0]);
    a->cur_left = a->block_size;
  }
  a->pool.clear();
  a->in_use.store(0, std::memory_order_relaxed);
}

void pt_arena_stats(PtArena* a, uint64_t* total, uint64_t* in_use,
                    uint64_t* peak, uint64_t* count) {
  if (!a) return;
  if (total) *total = a->total_allocated.load(std::memory_order_relaxed);
  if (in_use) *in_use = a->in_use.load(std::memory_order_relaxed);
  if (peak) *peak = a->peak.load(std::memory_order_relaxed);
  if (count) *count = a->alloc_count.load(std::memory_order_relaxed);
}

void pt_arena_free(PtArena* a) {
  if (!a) return;
  for (void* b : a->blocks) ::operator delete(b);
  delete a;
}

// ---------------------------------------------------------------------------
// Blocking MPMC ring buffer of byte blobs (DataLoader prefetch channel)
// ---------------------------------------------------------------------------

struct PtBlob {
  char* data;
  size_t size;
};

struct PtRing {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<PtBlob> q;
  size_t capacity;
  bool closed = false;
  std::atomic<uint64_t> pushed{0}, popped{0};
};

PtRing* pt_ring_new(size_t capacity) {
  auto* r = new PtRing();
  r->capacity = capacity ? capacity : 8;
  return r;
}

// Copies `data` in; blocks while full. Returns 0 ok, -1 closed.
int pt_ring_push(PtRing* r, const char* data, size_t size) {
  char* copy = static_cast<char*>(std::malloc(size ? size : 1));
  if (!copy) return -2;
  std::memcpy(copy, data, size);
  std::unique_lock<std::mutex> lk(r->mu);
  r->not_full.wait(lk, [&] { return r->q.size() < r->capacity || r->closed; });
  if (r->closed) {
    std::free(copy);
    return -1;
  }
  r->q.push_back({copy, size});
  r->pushed.fetch_add(1, std::memory_order_relaxed);
  r->not_empty.notify_one();
  return 0;
}

// Blocks while empty. On success caller owns *data (free with pt_blob_free).
// Returns 0 ok, -1 closed-and-drained, -3 timeout (timeout_ms >= 0).
int pt_ring_pop(PtRing* r, char** data, size_t* size, long timeout_ms) {
  std::unique_lock<std::mutex> lk(r->mu);
  auto ready = [&] { return !r->q.empty() || r->closed; };
  if (timeout_ms < 0) {
    r->not_empty.wait(lk, ready);
  } else if (!r->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    ready)) {
    return -3;
  }
  if (r->q.empty()) return -1;  // closed and drained
  PtBlob b = r->q.front();
  r->q.pop_front();
  r->popped.fetch_add(1, std::memory_order_relaxed);
  r->not_full.notify_one();
  *data = b.data;
  *size = b.size;
  return 0;
}

void pt_blob_free(char* data) { std::free(data); }

void pt_ring_close(PtRing* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  r->closed = true;
  r->not_empty.notify_all();
  r->not_full.notify_all();
}

size_t pt_ring_len(PtRing* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return r->q.size();
}

void pt_ring_free(PtRing* r) {
  if (!r) return;
  for (auto& b : r->q) std::free(b.data);
  delete r;
}

// ---------------------------------------------------------------------------
// Record shard files: [u64 magic][records: u32 crc, u32 len, bytes]
// with threaded readahead into a ring (the reference's recordio role)
// ---------------------------------------------------------------------------

static const uint64_t kMagic = 0x70745F7265634631ULL;  // "pt_recF1"

static uint32_t crc32_simple(const char* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) {
    c ^= static_cast<unsigned char>(p[i]);
    for (int k = 0; k < 8; ++k) c = (c >> 1) ^ (0xEDB88320u & (-(c & 1u)));
  }
  return ~c;
}

struct PtRecWriter {
  FILE* f;
  uint64_t n = 0;
};

PtRecWriter* pt_rec_writer_open(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  if (std::fwrite(&kMagic, 8, 1, f) != 1) {
    std::fclose(f);
    return nullptr;
  }
  auto* w = new PtRecWriter();
  w->f = f;
  return w;
}

int pt_rec_write(PtRecWriter* w, const char* data, uint32_t len) {
  uint32_t crc = crc32_simple(data, len);
  if (std::fwrite(&crc, 4, 1, w->f) != 1) return -1;
  if (std::fwrite(&len, 4, 1, w->f) != 1) return -1;
  if (len && std::fwrite(data, 1, len, w->f) != len) return -1;
  w->n++;
  return 0;
}

uint64_t pt_rec_writer_close(PtRecWriter* w) {
  uint64_t n = w->n;
  std::fclose(w->f);
  delete w;
  return n;
}

// Threaded shard reader: N reader threads stream records from a list of
// shard files into a ring buffer; consumers pop via pt_ring_pop.
struct PtShardReader {
  PtRing* ring;
  std::vector<std::string> paths;
  std::vector<std::thread> threads;
  std::atomic<int> active{0};
  std::atomic<int> errors{0};
  std::atomic<size_t> next_shard{0};
};

static void shard_worker(PtShardReader* sr) {
  for (;;) {
    size_t i = sr->next_shard.fetch_add(1);
    if (i >= sr->paths.size()) break;
    FILE* f = std::fopen(sr->paths[i].c_str(), "rb");
    if (!f) {
      sr->errors.fetch_add(1);
      continue;
    }
    uint64_t magic = 0;
    if (std::fread(&magic, 8, 1, f) != 1 || magic != kMagic) {
      sr->errors.fetch_add(1);
      std::fclose(f);
      continue;
    }
    std::vector<char> buf;
    for (;;) {
      uint32_t crc, len;
      if (std::fread(&crc, 4, 1, f) != 1) break;  // clean EOF
      if (std::fread(&len, 4, 1, f) != 1) {
        sr->errors.fetch_add(1);
        break;
      }
      buf.resize(len);
      if (len && std::fread(buf.data(), 1, len, f) != len) {
        sr->errors.fetch_add(1);
        break;
      }
      if (crc32_simple(buf.data(), len) != crc) {
        sr->errors.fetch_add(1);
        break;  // corruption: stop this shard
      }
      if (pt_ring_push(sr->ring, buf.data(), len) != 0) {
        std::fclose(f);
        return;  // ring closed: consumer is done
      }
    }
    std::fclose(f);
  }
  if (sr->active.fetch_sub(1) == 1) pt_ring_close(sr->ring);
}

PtShardReader* pt_shard_reader_start(const char** paths, int n_paths,
                                     int n_threads, size_t ring_capacity) {
  auto* sr = new PtShardReader();
  sr->ring = pt_ring_new(ring_capacity);
  for (int i = 0; i < n_paths; ++i) sr->paths.emplace_back(paths[i]);
  if (n_threads < 1) n_threads = 1;
  sr->active.store(n_threads);
  for (int i = 0; i < n_threads; ++i)
    sr->threads.emplace_back(shard_worker, sr);
  return sr;
}

PtRing* pt_shard_reader_ring(PtShardReader* sr) { return sr->ring; }
int pt_shard_reader_errors(PtShardReader* sr) { return sr->errors.load(); }

void pt_shard_reader_free(PtShardReader* sr) {
  if (!sr) return;
  pt_ring_close(sr->ring);
  for (auto& t : sr->threads)
    if (t.joinable()) t.join();
  pt_ring_free(sr->ring);
  delete sr;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Shuffle pool: bounded reservoir of byte blobs with uniform random pops.
// The native analog of the reference's buffered shuffle reader decorator
// (python/paddle/reader/decorator.py shuffle): producers push decoded
// samples without holding the GIL; consumers pop a uniformly random
// element once the pool has warmed up. xorshift64* keeps draws cheap and
// deterministic per seed.
// ---------------------------------------------------------------------------

struct PtShufflePool {
  std::mutex mu;
  std::condition_variable cv_push, cv_pop, cv_drain;
  std::vector<PtBlob> pool;
  size_t capacity;
  uint64_t rng;
  bool closed = false;
  // callers currently inside push/pop; pt_shuffle_free waits for this
  // to hit zero after close so a woken producer can't touch freed state
  int inflight = 0;
};

static uint64_t pt_xorshift(uint64_t* s) {
  uint64_t x = *s;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *s = x;
  return x * 0x2545F4914F6CDD1DULL;
}

extern "C" {

PtShufflePool* pt_shuffle_new(size_t capacity, uint64_t seed) {
  auto* p = new PtShufflePool();
  p->capacity = capacity ? capacity : 1;
  p->rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  p->pool.reserve(p->capacity);
  return p;
}

static void pt_shuffle_exit(PtShufflePool* p) {
  if (--p->inflight == 0 && p->closed) p->cv_drain.notify_all();
}

int pt_shuffle_push(PtShufflePool* p, const char* data, size_t size) {
  std::unique_lock<std::mutex> lk(p->mu);
  ++p->inflight;
  p->cv_push.wait(lk, [&] { return p->pool.size() < p->capacity ||
                                   p->closed; });
  if (p->closed) {
    pt_shuffle_exit(p);
    return -1;
  }
  char* copy = static_cast<char*>(std::malloc(size));
  if (!copy) {
    pt_shuffle_exit(p);
    return -2;
  }
  std::memcpy(copy, data, size);
  p->pool.push_back({copy, size});
  p->cv_pop.notify_one();
  pt_shuffle_exit(p);
  return 0;
}

// Pops a uniformly random element. min_fill: block until the pool holds
// at least this many (or is closed) so early pops still shuffle well.
int pt_shuffle_pop(PtShufflePool* p, char** data, size_t* size,
                   size_t min_fill, long timeout_ms) {
  std::unique_lock<std::mutex> lk(p->mu);
  ++p->inflight;
  auto ready = [&] {
    return p->pool.size() >= (p->closed ? 1 : (min_fill ? min_fill : 1)) ||
           (p->closed && p->pool.empty());
  };
  if (timeout_ms < 0) {
    p->cv_pop.wait(lk, ready);
  } else if (!p->cv_pop.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                 ready)) {
    pt_shuffle_exit(p);
    return 1;  // timeout
  }
  if (p->pool.empty()) {
    pt_shuffle_exit(p);
    return -1;  // closed and drained
  }
  size_t i = static_cast<size_t>(pt_xorshift(&p->rng) % p->pool.size());
  *data = p->pool[i].data;
  *size = p->pool[i].size;
  p->pool[i] = p->pool.back();
  p->pool.pop_back();
  p->cv_push.notify_one();
  pt_shuffle_exit(p);
  return 0;
}

size_t pt_shuffle_len(PtShufflePool* p) {
  std::lock_guard<std::mutex> lk(p->mu);
  return p->pool.size();
}

void pt_shuffle_close(PtShufflePool* p) {
  std::lock_guard<std::mutex> lk(p->mu);
  p->closed = true;
  p->cv_pop.notify_all();
  p->cv_push.notify_all();
}

void pt_shuffle_free(PtShufflePool* p) {
  {
    // close + drain: wake every blocked push/pop and wait until the last
    // one has left the monitor, so delete cannot race a woken producer
    std::unique_lock<std::mutex> lk(p->mu);
    p->closed = true;
    p->cv_pop.notify_all();
    p->cv_push.notify_all();
    p->cv_drain.wait(lk, [&] { return p->inflight == 0; });
  }
  for (auto& b : p->pool) std::free(b.data);
  delete p;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// MultiSlot sample parser (the reference's C++ data_feed.cc role:
// MultiSlotDataFeed::ParseOneInstance). One sample per line; per slot a
// count-prefixed group of values. Dense slots must match slot_sizes[i].
// ---------------------------------------------------------------------------

#include <cstdlib>

extern "C" {

// Parses samples from text[0..len). outs[i] receives slot i's values,
// sample-major: float32 buffers when slot_is_float[i], int64 otherwise;
// each caller-allocated with capacity max_samples * slot_sizes[i].
// text must be NUL-terminated (CPython bytes are) — strtol/strtof stop
// there. Tokens NEVER cross a newline: a line with too few values is a
// format error, not a frame-shifted read into the next sample; trailing
// extra tokens on a line are an error too (reference MultiSlotDataFeed
// semantics). Blank / whitespace-only lines are skipped. Returns the
// number of samples parsed, or -(line_index+1) on a format error at
// that (0-based, raw-text) line.
long pt_multislot_parse(const char* text, size_t len, int n_slots,
                        const long* slot_sizes, const int* slot_is_float,
                        void** outs, long max_samples) {
  const char* p = text;
  const char* end = text + len;
  long sample = 0;
  long line = 0;
  auto skip_sp = [&](const char* q) {
    // every non-newline whitespace strtol/strtof would skip must be
    // consumed HERE, or a token could silently cross the '\n' check
    while (q < end && (*q == ' ' || *q == '\t' || *q == '\r' ||
                       *q == '\v' || *q == '\f')) ++q;
    return q;
  };
  while (p < end && sample < max_samples) {
    // skip blank / whitespace-only lines (counting them)
    for (;;) {
      p = skip_sp(p);
      if (p < end && *p == '\n') {
        ++line;
        ++p;
        continue;
      }
      break;
    }
    if (p >= end) break;
    for (int s = 0; s < n_slots; ++s) {
      p = skip_sp(p);
      if (p >= end || *p == '\n') return -(line + 1);  // missing count
      char* next = nullptr;
      long n = std::strtol(p, &next, 10);
      if (next == p) return -(line + 1);
      p = next;
      if (n != slot_sizes[s]) return -(line + 1);  // dense-size mismatch
      for (long j = 0; j < n; ++j) {
        p = skip_sp(p);
        if (p >= end || *p == '\n') return -(line + 1);  // short line
        if (slot_is_float[s]) {
          static_cast<float*>(outs[s])[sample * n + j] =
              std::strtof(p, &next);
        } else {
          static_cast<long long*>(outs[s])[sample * n + j] =
              std::strtoll(p, &next, 10);
        }
        if (next == p) return -(line + 1);
        p = next;
      }
    }
    // only whitespace may remain on the line
    p = skip_sp(p);
    if (p < end && *p != '\n') return -(line + 1);  // trailing tokens
    if (p < end) ++p;
    ++line;
    ++sample;
  }
  return sample;
}

}  // extern "C"
