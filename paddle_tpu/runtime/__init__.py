"""Native host runtime bindings (ctypes over libptruntime.so).

See cc/ptruntime.cc for what each piece replaces in the reference. The
library is compiled on first use with the baked g++ toolchain and cached
next to the source; a pure-Python fallback keeps the pipeline functional if
no compiler is available.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(__file__)
_SO = os.path.join(_HERE, "libptruntime.so")
_SRC = os.path.join(_HERE, "cc", "ptruntime.cc")

_lib = None
_lib_lock = threading.Lock()


def _build():
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)


def get_lib():
    """Load (building if needed) the native runtime; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_SO)
            if not hasattr(lib, "pt_multislot_parse"):
                # stale .so from older source with equal/newer mtime
                # (docker COPY / zip extraction): rebuild once
                _build()
                lib = ctypes.CDLL(_SO)
        except Exception:
            return None
        # signatures (a missing symbol means an unusable lib:
        # fall back to pure Python rather than crash consumers)
        try:
            # signatures
            lib.pt_arena_new.restype = ctypes.c_void_p
            lib.pt_arena_new.argtypes = [ctypes.c_size_t]
            lib.pt_arena_alloc.restype = ctypes.c_void_p
            lib.pt_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.pt_arena_reset.argtypes = [ctypes.c_void_p]
            lib.pt_arena_free.argtypes = [ctypes.c_void_p]
            lib.pt_arena_stats.argtypes = [ctypes.c_void_p] + \
                [ctypes.POINTER(ctypes.c_uint64)] * 4
            lib.pt_ring_new.restype = ctypes.c_void_p
            lib.pt_ring_new.argtypes = [ctypes.c_size_t]
            lib.pt_ring_push.restype = ctypes.c_int
            lib.pt_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_size_t]
            lib.pt_ring_pop.restype = ctypes.c_int
            lib.pt_ring_pop.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_void_p),
                                        ctypes.POINTER(ctypes.c_size_t),
                                        ctypes.c_long]
            lib.pt_blob_free.argtypes = [ctypes.c_void_p]
            lib.pt_ring_close.argtypes = [ctypes.c_void_p]
            lib.pt_ring_len.restype = ctypes.c_size_t
            lib.pt_ring_len.argtypes = [ctypes.c_void_p]
            lib.pt_ring_free.argtypes = [ctypes.c_void_p]
            lib.pt_rec_writer_open.restype = ctypes.c_void_p
            lib.pt_rec_writer_open.argtypes = [ctypes.c_char_p]
            lib.pt_rec_write.restype = ctypes.c_int
            lib.pt_rec_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint32]
            lib.pt_rec_writer_close.restype = ctypes.c_uint64
            lib.pt_rec_writer_close.argtypes = [ctypes.c_void_p]
            lib.pt_shard_reader_start.restype = ctypes.c_void_p
            lib.pt_shard_reader_start.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
                ctypes.c_size_t]
            lib.pt_shard_reader_ring.restype = ctypes.c_void_p
            lib.pt_shard_reader_ring.argtypes = [ctypes.c_void_p]
            lib.pt_shard_reader_errors.restype = ctypes.c_int
            lib.pt_shard_reader_errors.argtypes = [ctypes.c_void_p]
            lib.pt_shard_reader_free.argtypes = [ctypes.c_void_p]
            lib.pt_shuffle_new.restype = ctypes.c_void_p
            lib.pt_shuffle_new.argtypes = [ctypes.c_size_t, ctypes.c_uint64]
            lib.pt_shuffle_push.restype = ctypes.c_int
            lib.pt_shuffle_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_size_t]
            lib.pt_shuffle_pop.restype = ctypes.c_int
            lib.pt_shuffle_pop.argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(ctypes.c_void_p),
                                           ctypes.POINTER(ctypes.c_size_t),
                                           ctypes.c_size_t, ctypes.c_long]
            lib.pt_shuffle_len.restype = ctypes.c_size_t
            lib.pt_shuffle_len.argtypes = [ctypes.c_void_p]
            lib.pt_shuffle_close.argtypes = [ctypes.c_void_p]
            lib.pt_shuffle_free.argtypes = [ctypes.c_void_p]
            lib.pt_multislot_parse.restype = ctypes.c_long
            lib.pt_multislot_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_long]
        except AttributeError:
            return None
        _lib = lib
        return _lib


class RingBuffer:
    """Blocking byte-blob channel; native when possible, queue fallback."""

    def __init__(self, capacity=8):
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.pt_ring_new(capacity)
            self._q = None
        else:  # pure-python fallback
            import queue

            self._h = None
            self._q = queue.Queue(maxsize=capacity)
        self._closed = False

    def push(self, data: bytes) -> bool:
        if self._h is not None:
            return self._lib.pt_ring_push(self._h, data, len(data)) == 0
        try:
            while True:
                if self._closed:
                    return False
                try:
                    self._q.put(data, timeout=0.1)
                    return True
                except Exception:
                    continue
        except Exception:
            return False

    def pop(self, timeout_ms=-1):
        """bytes, or None when closed-and-drained."""
        if self._h is not None:
            p = ctypes.c_void_p()
            n = ctypes.c_size_t()
            rc = self._lib.pt_ring_pop(self._h, ctypes.byref(p),
                                       ctypes.byref(n), timeout_ms)
            if rc == -1:
                return None
            if rc == -3:
                raise TimeoutError("ring pop timed out")
            data = ctypes.string_at(p.value, n.value)
            self._lib.pt_blob_free(p)
            return data
        import queue

        deadline = None if timeout_ms < 0 else timeout_ms / 1000.0
        while True:
            try:
                return self._q.get(timeout=0.1 if deadline is None else deadline)
            except queue.Empty:
                if self._closed and self._q.empty():
                    return None
                if deadline is not None:
                    raise TimeoutError("ring pop timed out")

    def __len__(self):
        if self._h is not None:
            return self._lib.pt_ring_len(self._h)
        return self._q.qsize()

    def close(self):
        self._closed = True
        if self._h is not None:
            self._lib.pt_ring_close(self._h)

    def __del__(self):
        try:
            if self._h is not None and self._lib is not None:
                self._lib.pt_ring_free(self._h)
                self._h = None
        except Exception:
            pass


class Arena:
    """Host staging allocator with stats (ref: memory/allocation)."""

    def __init__(self, block_size=1 << 20):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pt_arena_new(block_size)

    def alloc(self, n) -> int:
        return self._lib.pt_arena_alloc(self._h, n)

    def reset(self):
        self._lib.pt_arena_reset(self._h)

    def stats(self):
        vals = [ctypes.c_uint64() for _ in range(4)]
        self._lib.pt_arena_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"total_allocated": vals[0].value, "in_use": vals[1].value,
                "peak": vals[2].value, "alloc_count": vals[3].value}

    def __del__(self):
        try:
            if getattr(self, "_h", None) is not None:
                self._lib.pt_arena_free(self._h)
                self._h = None
        except Exception:
            pass


class RecordWriter:
    """Length-prefixed CRC'd record shard writer."""

    def __init__(self, path):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pt_rec_writer_open(os.fsencode(path))
        if not self._h:
            raise OSError(f"cannot open {path}")

    def write(self, data: bytes):
        if self._lib.pt_rec_write(self._h, data, len(data)) != 0:
            raise OSError("record write failed")

    def close(self) -> int:
        n = self._lib.pt_rec_writer_close(self._h)
        self._h = None
        return n

    def __enter__(self):
        return self

    def __exit__(self, *a):
        if self._h:
            self.close()


class ShardReader:
    """Threaded readahead over record shards; iterates raw record bytes."""

    def __init__(self, paths, n_threads=2, ring_capacity=64):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[os.fsencode(p) for p in paths])
        self._h = lib.pt_shard_reader_start(arr, len(paths), n_threads,
                                            ring_capacity)
        self._ring = lib.pt_shard_reader_ring(self._h)

    def __iter__(self):
        return self

    def __next__(self):
        p = ctypes.c_void_p()
        n = ctypes.c_size_t()
        rc = self._lib.pt_ring_pop(self._ring, ctypes.byref(p),
                                   ctypes.byref(n), -1)
        if rc == -1:
            if self._lib.pt_shard_reader_errors(self._h):
                raise OSError("shard reader encountered corrupt records")
            raise StopIteration
        data = ctypes.string_at(p.value, n.value)
        self._lib.pt_blob_free(p)
        return data

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pt_shard_reader_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShufflePool:
    """Bounded reservoir with uniform random pops — the native analog of
    the buffered shuffle reader (cc: PtShufflePool); python-queue-free
    so producers can feed it from worker threads without GIL churn.
    Falls back to a pure-python reservoir when the library is absent."""

    def __init__(self, capacity=1024, seed=0, min_fill=None):
        self._min_fill = min(min_fill if min_fill is not None
                             else capacity // 2, capacity)
        lib = get_lib()
        self._lib = lib
        import threading as _t

        # liveness guard: counts callers inside native push/pop so free
        # can wait for them (mirrors the C-side inflight drain; this
        # layer also stops NEW callers once the handle is retired)
        self._guard = _t.Condition()
        self._users = 0
        if lib is not None:
            self._h = lib.pt_shuffle_new(capacity, seed or 0)
        else:
            import random

            self._h = None
            self._pool = []
            self._rng = random.Random(seed)
            self._cap = capacity
            self._closed = False
            self._cv = _t.Condition()

    def _enter(self):
        """Claim the native handle for one call; None once retired."""
        with self._guard:
            if self._h is None:
                return None
            self._users += 1
            return self._h

    def _exit(self):
        with self._guard:
            self._users -= 1
            if self._users == 0:
                self._guard.notify_all()

    def push(self, data: bytes) -> bool:
        h = self._enter()
        if h is not None:
            try:
                rc = self._lib.pt_shuffle_push(h, data, len(data))
            finally:
                self._exit()
            if rc == -2:  # malloc failure is an error, not a quiet stop
                raise MemoryError("ShufflePool: native allocation failed")
            return rc == 0
        if self._lib is not None:
            return False  # native pool already freed
        with self._cv:
            while len(self._pool) >= self._cap and not self._closed:
                self._cv.wait(0.1)
            if self._closed:
                return False
            self._pool.append(bytes(data))
            self._cv.notify_all()
            return True

    def pop(self, timeout_ms=-1):
        """A uniformly random blob; None when closed and drained; raises
        TimeoutError when ``timeout_ms`` elapses first (a slow producer
        is not end-of-stream)."""
        h = self._enter() if self._lib is not None else None
        if h is not None:
            try:
                data = ctypes.c_void_p()
                size = ctypes.c_size_t()
                rc = self._lib.pt_shuffle_pop(h, ctypes.byref(data),
                                              ctypes.byref(size),
                                              self._min_fill, timeout_ms)
            finally:
                self._exit()
            if rc == 1:
                raise TimeoutError(
                    f"ShufflePool.pop: no sample within {timeout_ms}ms")
            if rc != 0:
                return None
            out = ctypes.string_at(data, size.value)
            self._lib.pt_blob_free(data)
            return out
        if self._lib is not None:
            return None  # native pool already freed
        import time as _time

        deadline = None if timeout_ms < 0 \
            else _time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while True:
                ready = len(self._pool) >= (1 if self._closed
                                            else max(self._min_fill, 1))
                if ready or (self._closed and not self._pool):
                    break
                if deadline is not None and _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"ShufflePool.pop: no sample within {timeout_ms}ms")
                self._cv.wait(0.1)
            if not self._pool:
                return None
            i = self._rng.randrange(len(self._pool))
            self._pool[i], self._pool[-1] = self._pool[-1], self._pool[i]
            out = self._pool.pop()
            self._cv.notify_all()
            return out

    def __len__(self):
        h = self._enter() if self._lib is not None else None
        if h is not None:
            try:
                return self._lib.pt_shuffle_len(h)
            finally:
                self._exit()
        if self._lib is not None:
            return 0
        with self._cv:
            return len(self._pool)

    def close(self):
        h = self._enter() if self._lib is not None else None
        if h is not None:
            try:
                self._lib.pt_shuffle_close(h)
            finally:
                self._exit()
            return
        if self._lib is not None:
            return
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def __del__(self):
        try:
            if self._lib is None or self._h is None:
                return
            # retire the handle first so no NEW caller can enter, then
            # wait for in-flight push/pop to leave; the C free() adds a
            # second drain (closed + inflight cv) for non-python callers
            with self._guard:
                h, self._h = self._h, None
                self._lib.pt_shuffle_close(h)  # wakes blocked callers
                while self._users:
                    self._guard.wait(0.1)
            self._lib.pt_shuffle_free(h)
        except Exception:
            pass


def multislot_parse(text, slot_sizes, slot_is_float):
    """Native MultiSlot sample parsing (the reference data_feed.cc role:
    MultiSlotDataFeed::ParseOneInstance). ``text``: bytes of one file's
    samples; returns a list of sample-major arrays, one per slot
    (float32 or int64, shape (n_samples, slot_size)), or None when the
    native library is unavailable (caller falls back to Python parsing).
    Raises ValueError with the 0-based line index on a format error.
    """
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    if isinstance(text, str):
        text = text.encode()
    # upper bound on samples: number of newlines + 1
    max_samples = text.count(b"\n") + 1
    n = len(slot_sizes)
    sizes = (ctypes.c_long * n)(*[int(s) for s in slot_sizes])
    isf = (ctypes.c_int * n)(*[1 if f else 0 for f in slot_is_float])
    bufs = [np.empty((max_samples, int(sz)),
                     np.float32 if f else np.int64)
            for sz, f in zip(slot_sizes, slot_is_float)]
    outs = (ctypes.c_void_p * n)(*[b.ctypes.data for b in bufs])
    got = lib.pt_multislot_parse(text, len(text), n, sizes, isf, outs,
                                 max_samples)
    if got < 0:
        raise ValueError(f"malformed MultiSlot sample at line {-got - 1}")
    return [b[:got] for b in bufs]
