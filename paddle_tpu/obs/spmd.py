"""SPMD observability: collective accounting, sharding introspection,
per-device telemetry.

The reference's ParallelExecutor ran its NCCL all-reduces blind — the
only comm visibility was NCCL debug logs. Here the collectives are
*compiled into* the executable by GSPMD, which means the executable's
own HLO text is the ground truth for what the step moves over ICI:
every all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all appears with its payload shape and replica groups. This
module turns that text into numbers:

- ``parse_hlo_collectives`` / ``collective_profile`` — per-executable
  **CollectiveProfile**: op counts and byte volumes per collective
  kind, attributed to mesh axes by matching each op's replica groups
  against the device mesh (EQuARX, arXiv:2506.17615, treats exactly
  this accounting as the lever for distributed-XLA speedups).
- ``comm_roofline`` — compose collective bytes with the chip's ICI
  bandwidth (env ``PADDLE_TPU_ICI_BW`` or the per-chip table) and the
  step's FLOPs vs peak (``obs.mfu``) into a compute-vs-comm breakdown —
  the comm/compute-overlap attribution the MLPerf TPU-pod scaling
  study (arXiv:1909.09756) identifies as where scaling losses live.
- ``sharding_report`` — **ShardingReport** for one Executor cache
  entry: feed / persistable / fetch → mesh axes + per-device byte
  footprint (what the fleet layer's per-rank log spew never totaled).
- ``device_memory_stats`` / ``update_device_gauges`` — live per-device
  HBM gauges from ``device.memory_stats()`` where the backend exposes
  them (TPU does; host CPU reports None), including the high-water
  device; samples land in ``obs.metrics`` gauges and — when span
  tracing is on — per-device pid lanes in the Chrome trace.

Byte convention: an op's ``bytes`` is the byte size of its HLO result
shape (tuple results of sync multi-operand ops summed; async ``-start``
tuples pick the result element) — the payload each participant holds
after the op. ``wire_bytes`` applies the standard ring-algorithm
factors to the FULL payload moved through the group (all-reduce
``2(n-1)/n``, all-gather/all-to-all ``(n-1)/n`` — their result IS the
full payload; reduce-scatter ``(n-1)/n`` of ``result x group_size``,
since its result is one shard; collective-permute ``1``) so the
roofline reflects actual link traffic.

Everything here is off the step path: parsing runs inside the lazy
``obs.mfu.entry_analysis`` (daemon-thread, cached per cache entry), and
the journal hooks follow the ``if ACTIVE is None`` zero-overhead
contract.
"""
from __future__ import annotations

import os
import re

import numpy as np

__all__ = [
    "COLLECTIVE_KINDS", "parse_hlo_collectives", "collective_profile",
    "merge_profiles", "ICI_BW_BY_KIND", "ici_bandwidth", "comm_roofline",
    "sharding_report", "sharding_summary", "device_memory_stats",
    "update_device_gauges", "profile_jit_fn", "mesh_info", "wire_factor",
]

# canonical collective kinds (HLO op mnemonics); async forms appear as
# <kind>-start / <kind>-done pairs — -start carries the payload, -done
# is bookkeeping and must not double count
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "s4": 1, "u4": 1,
}

# one HLO instruction: "%name = TYPE opkind(", where TYPE is either a
# single "f32[128,64]{1,0}" shape or a tuple "(f32[..], f32[..])"
_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|\w+\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")

# sub-byte/byte integer payloads = a quantized exchange is on the wire
# (dist.gradcomm int8 all-reduce, int4 weight gathers); bf16/f16 are
# reduced-precision but not "quantized" in this accounting
_QUANT_DTYPES = frozenset(("s8", "u8", "s4", "u4"))
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|"
                        r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")

# ring-algorithm wire-traffic factors per participant, as a multiple of
# the op's RESULT bytes (n = group size). all-gather/all-to-all results
# are the full gathered payload; a reduce-scatter's result is one shard
# of it, so the (n-1)/n factor applies to result*n = (n-1) — without
# that, a ZeRO/FSDP-style reduce-scatter-dominated step would read ~n x
# too cheap on the roofline
_WIRE_FACTOR = {
    "all-reduce": lambda n: 2.0 * (n - 1) / n if n > 1 else 0.0,
    "all-gather": lambda n: (n - 1) / n if n > 1 else 0.0,
    "reduce-scatter": lambda n: float(n - 1),
    "all-to-all": lambda n: (n - 1) / n if n > 1 else 0.0,
    "collective-permute": lambda n: 1.0,
}


def wire_factor(kind, group_size):
    """Public read of the ring-algorithm wire-traffic factor for one
    collective kind at one group size — the SAME convention
    ``collective_profile`` measures by, so a predictor (fleet.planner)
    that prices with this factor is directly comparable to the
    HLO-measured profile."""
    return _WIRE_FACTOR[kind](int(group_size))


def _shape_bytes(type_str, kind=None, is_async=False):
    """Byte size of one HLO result type ("f32[4,4]{1,0}" or a tuple
    "(f32[4], bf16[8,2])"). Sync tuple results (multi-operand
    all-to-all) sum — together they are the payload. Async ``-start``
    results are (operand, result[, context...]) bundles: summing would
    double-count, so pick the element playing the result role — the
    largest (all-gather grows, all-reduce/permute are same-shape, the
    u32 context scalars lose), except reduce-scatter, whose result is
    the SMALLEST non-scalar element. Unknown dtypes count 4 bytes."""
    sizes = []
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        sizes.append(n * _HLO_DTYPE_BYTES.get(dt, 4))
    if not sizes:
        return 0
    if is_async and len(sizes) > 1:
        if kind == "reduce-scatter":
            tensors = [s for s in sizes if s > 8] or sizes
            return min(tensors)
        return max(sizes)
    return sum(sizes)


def _is_quantized(type_str):
    """Whether the op's tensor payload is integer-quantized (s8/u8/
    s4/u4): every non-scalar element of the result type is a quantized
    dtype. Scalar elements (async context tokens) are ignored; an op
    with no non-scalar payload is not quantized."""
    dts = [dt for dt, dims in _SHAPE_RE.findall(type_str) if dims]
    return bool(dts) and all(dt in _QUANT_DTYPES for dt in dts)


def _iota_groups(spec):
    """Expand the iota replica-group form "[G,S]<=[d0,d1,..]T(p..)" into
    explicit groups: reshape iota(prod(dims)) by dims, transpose by the
    optional permutation, then reshape to (G, S)."""
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", spec)
    if m is None:
        raise ValueError(f"unparseable replica_groups {spec!r}")
    gshape = [int(x) for x in m.group(1).split(",")]
    dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    return ids.reshape(gshape).tolist()


def _parse_groups(attr):
    """Explicit "{{0,1},{2,3}}" or iota "[2,4]<=[8]T(..)" replica groups
    -> list of lists of device ids."""
    if attr.startswith("{"):
        return [[int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([0-9,\s]*)\}", attr[1:-1])]
    return _iota_groups(attr)


def mesh_info(mesh):
    """Normalize a ``jax.sharding.Mesh`` (or an (axes, ids) pair already
    in this form) to ``(axes_dict, device_id_array)`` — the inputs the
    replica-group attribution needs. Returns (None, None) for None."""
    if mesh is None:
        return None, None
    if isinstance(mesh, tuple) and len(mesh) == 2:
        axes, ids = mesh
        return dict(axes), (None if ids is None else np.asarray(ids))
    axes = dict(mesh.shape)
    ids = np.vectorize(lambda d: int(d.id))(mesh.devices)
    return axes, ids


def _axis_groups(axes, ids, subset):
    """Expected replica groups for a collective over the mesh-axis
    ``subset``: devices sharing every coordinate OUTSIDE the subset form
    one group."""
    names = list(axes)
    keep = [i for i, n in enumerate(names) if n not in subset]
    move = [i for i, n in enumerate(names) if n in subset]
    perm = keep + move
    arr = np.transpose(ids.reshape([axes[n] for n in names]), perm)
    gsz = int(np.prod([axes[names[i]] for i in move])) if move else 1
    return arr.reshape(-1, gsz)


def _attribute_axes(groups, axes, ids):
    """Match one op's replica groups against every mesh-axis subset;
    returns the '+'-joined axis names ('data', 'model+sp', ...) or None
    when the groups match no axis combination (or no mesh is known)."""
    if axes is None or ids is None or not groups:
        return None
    want = frozenset(frozenset(g) for g in groups)
    names = list(axes)
    # smallest subsets first so a 1-axis collective is named by its axis
    for size in range(1, len(names) + 1):
        from itertools import combinations

        for subset in combinations(names, size):
            expect = _axis_groups(axes, ids, set(subset))
            if frozenset(frozenset(g.tolist()) for g in expect) == want:
                return "+".join(subset)
    return None


def parse_hlo_collectives(hlo_text, mesh=None):
    """Scan optimized HLO text for collective ops. Returns a list of
    ``{"kind", "bytes", "group_size", "n_groups", "axes"}`` dicts — one
    per instruction (async -start/-done pairs counted once, on -start).

    ``mesh`` (a jax Mesh, or an ``(axes_dict, device_id_array)`` pair)
    enables mesh-axis attribution via replica groups; without it
    ``axes`` is None.
    """
    axes, ids = mesh_info(mesh)
    ndev = int(np.prod(list(axes.values()))) if axes else None
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        type_str, kind, async_part = m.group(1), m.group(2), m.group(3)
        if async_part == "-done":
            continue  # payload already counted on the -start
        groups = None
        gm = _GROUPS_RE.search(line)
        if gm is not None:
            try:
                groups = _parse_groups(gm.group(1))
            except ValueError:
                groups = None
        elif kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            if pm is not None:
                # pairs aren't groups; the permute ring spans the set of
                # participating devices
                devs = sorted({int(x) for x in
                               re.findall(r"\d+", pm.group(1))})
                groups = [devs] if devs else None
        if groups and not groups[0]:
            groups = None
        gsize = len(groups[0]) if groups else (ndev or 1)
        ops.append({
            "kind": kind,
            "bytes": _shape_bytes(type_str, kind=kind,
                                  is_async=async_part == "-start"),
            "group_size": gsize,
            "n_groups": len(groups) if groups else None,
            "axes": _attribute_axes(groups, axes, ids),
            "quant": _is_quantized(type_str),
        })
    return ops


def collective_profile(hlo_text, mesh=None):
    """The **CollectiveProfile** of one compiled executable: per-kind
    op counts and byte volumes, total/wire bytes, and a per-mesh-axis
    byte breakdown. All byte figures are per execution of the
    executable (one training step for an Executor entry)."""
    ops = parse_hlo_collectives(hlo_text, mesh=mesh)
    counts, bytes_, by_axis = {}, {}, {}
    wire = quant = quant_wire = 0.0
    for op in ops:
        k = op["kind"]
        counts[k] = counts.get(k, 0) + 1
        bytes_[k] = bytes_.get(k, 0) + op["bytes"]
        w = op["bytes"] * _WIRE_FACTOR[k](op["group_size"])
        wire += w
        if op.get("quant"):
            quant += op["bytes"]
            quant_wire += w
        ax = op["axes"] or "?"
        by_axis[ax] = by_axis.get(ax, 0) + op["bytes"]
    return {
        "n_ops": len(ops),
        "counts": counts,
        "bytes": bytes_,
        "total_bytes": sum(bytes_.values()),
        "wire_bytes": int(round(wire)),
        # the integer-payload (s8/u8/s4/u4) share of the above — the
        # dist.gradcomm int8 exchange's wire footprint, rendered as the
        # shard_report roofline's "quantized wire bytes" column
        "quant_bytes": int(round(quant)),
        "quant_wire_bytes": int(round(quant_wire)),
        "by_axis": by_axis,
    }


def merge_profiles(profiles):
    """Sum several CollectiveProfiles (e.g. one per microbatch phase)
    into one; Nones are skipped. Returns None when nothing to merge."""
    profiles = [p for p in profiles if p]
    if not profiles:
        return None
    out = {"n_ops": 0, "counts": {}, "bytes": {}, "total_bytes": 0,
           "wire_bytes": 0, "quant_bytes": 0, "quant_wire_bytes": 0,
           "by_axis": {}}
    for p in profiles:
        out["n_ops"] += p.get("n_ops", 0)
        out["total_bytes"] += p.get("total_bytes", 0)
        out["wire_bytes"] += p.get("wire_bytes", 0)
        out["quant_bytes"] += p.get("quant_bytes", 0)
        out["quant_wire_bytes"] += p.get("quant_wire_bytes", 0)
        for field in ("counts", "bytes", "by_axis"):
            for k, v in (p.get(field) or {}).items():
                out[field][k] = out[field].get(k, 0) + v
    return out


# -- comm roofline -----------------------------------------------------------

# per-chip aggregate ICI bandwidth, bytes/s (published per-chip interconnect
# figures: v4 2400 Gb/s, v5e 1600 Gb/s, v5p 4800 Gb/s, v6e 3584 Gb/s)
ICI_BW_BY_KIND = {
    "TPU v4": 2400e9 / 8,
    "TPU v5e": 1600e9 / 8,
    "TPU v5 lite": 1600e9 / 8,
    "TPU v5p": 4800e9 / 8,
    "TPU v6e": 3584e9 / 8,
}


def ici_bandwidth():
    """ICI bytes/s for the roofline: env ``PADDLE_TPU_ICI_BW`` wins,
    else the per-chip table keyed on the backend's device kind. ``None``
    when nothing is known (host CPU) — and NEVER forces jax backend
    creation to find out (same guard discipline as ``mfu.peak_flops``)."""
    env = os.environ.get("PADDLE_TPU_ICI_BW", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        try:
            from jax._src import xla_bridge as _xb

            if hasattr(_xb, "_backends") and not _xb._backends:
                return None  # probing would pin/init the platform
        except ImportError:
            pass
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for k, v in ICI_BW_BY_KIND.items():
        if k.lower() in kind.lower():
            return v
    return None


def comm_roofline(profile, flops=None, peak=None, bw=None):
    """Compute-vs-comm step breakdown from a CollectiveProfile and the
    step's FLOPs: ideal comm time (wire bytes / ICI bandwidth), ideal
    compute time (FLOPs / peak), the comm share of the step under
    perfect overlap-free execution, and which resource bounds the step.
    Missing inputs (no bandwidth known, no FLOPs yet) yield None fields
    rather than made-up numbers."""
    from .mfu import peak_flops

    bw = bw if bw is not None else ici_bandwidth()
    peak = peak if peak is not None else peak_flops()
    wire = (profile or {}).get("wire_bytes", 0)
    comm_s = (wire / bw) if (bw and wire) else (0.0 if not wire else None)
    compute_s = (flops / peak) if (flops and peak) else None
    out = {"comm_bytes": (profile or {}).get("total_bytes", 0),
           "wire_bytes": wire, "ici_bw": bw,
           "comm_time_s": comm_s, "compute_time_s": compute_s,
           "comm_share": None, "bound": None}
    if comm_s is not None and compute_s is not None:
        total = comm_s + compute_s
        out["comm_share"] = comm_s / total if total > 0 else 0.0
        out["bound"] = "comm" if comm_s > compute_s else "compute"
    return out


# -- sharding introspection --------------------------------------------------


def _spec_str(sharding):
    """Render a NamedSharding's PartitionSpec compactly; replicated
    placements render as 'replicated'."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return "replicated"
    parts = [("+".join(p) if isinstance(p, tuple) else str(p))
             for p in spec if p is not None]
    return ",".join(parts) if parts else "replicated"


def _devices_spanned(sharding, axes):
    """How many devices one shard's bytes divide across."""
    spec = getattr(sharding, "spec", None)
    if spec is None or axes is None:
        return 1
    n = 1
    for p in spec:
        for name in (p if isinstance(p, tuple) else (p,)):
            if name is not None:
                n *= axes.get(name, 1)
    return n


def _struct_bytes(struct):
    n = 1
    for s in struct.shape:
        n *= int(s)
    return n * np.dtype(struct.dtype).itemsize


def sharding_report(compiled):
    """The **ShardingReport** of one Executor cache entry: mesh axes,
    and per variable (feed / updated-persistable / frozen-persistable /
    fetch) the partition spec, total bytes, and per-device byte
    footprint. Built from metadata captured at ``_build`` — no device
    transfer, no XLA work."""
    axes = getattr(compiled, "mesh_axes", None)
    feed_sh = getattr(compiled, "feed_shardings", None)
    structs = getattr(compiled, "arg_structs", None)
    rows = []

    def row(name, role, struct, sharding):
        total = _struct_bytes(struct) if struct is not None else None
        span = _devices_spanned(sharding, axes)
        rows.append({
            "name": name, "role": role,
            "shape": (list(struct.shape) if struct is not None else None),
            "dtype": (str(np.dtype(struct.dtype))
                      if struct is not None else None),
            "spec": _spec_str(sharding) if sharding is not None
            else "replicated",
            "bytes": total,
            "per_device_bytes": (total // span if total is not None
                                 else None),
        })

    feed_structs = structs[0] if structs else []
    for i, name in enumerate(getattr(compiled, "feed_names", ()) or ()):
        st = feed_structs[i] if i < len(feed_structs) else None
        sh = feed_sh[i] if feed_sh is not None and i < len(feed_sh) else None
        row(name, "feed", st, sh)
    upd_structs = structs[1] if structs else []
    for i, name in enumerate(getattr(compiled, "updated", ()) or ()):
        row(name, "persistable:updated",
            upd_structs[i] if i < len(upd_structs) else None, None)
    frz_structs = structs[2] if structs else []
    for i, name in enumerate(getattr(compiled, "frozen", ()) or ()):
        row(name, "persistable:frozen",
            frz_structs[i] if i < len(frz_structs) else None, None)
    for name in getattr(compiled, "fetch_names", ()) or ():
        # fetches replicate (executor out_shardings); shapes are only
        # known post-lowering, so bytes stay None here
        rows.append({"name": name, "role": "fetch", "shape": None,
                     "dtype": None, "spec": "replicated", "bytes": None,
                     "per_device_bytes": None})
    known = [r["bytes"] for r in rows if r["bytes"] is not None]
    per_dev = [r["per_device_bytes"] for r in rows
               if r["per_device_bytes"] is not None]
    return {
        "program_uid": getattr(compiled, "program_uid", None),
        "program_version": getattr(compiled, "program_version", None),
        "mesh": axes,
        "vars": rows,
        "total_bytes": sum(known) if known else None,
        "per_device_bytes": sum(per_dev) if per_dev else None,
    }


def sharding_summary(compiled, max_vars=16):
    """Bounded summary of ``sharding_report`` for the journal's
    per-compile ``sharding`` event: mesh axes, aggregate footprints, and
    the ``max_vars`` largest variables (by bytes) with their specs."""
    rep = sharding_report(compiled)
    rows = sorted(rep["vars"], key=lambda r: -(r["bytes"] or 0))
    return {
        "program_uid": rep["program_uid"],
        "program_version": rep["program_version"],
        "mesh": rep["mesh"],
        "n_vars": len(rep["vars"]),
        "total_bytes": rep["total_bytes"],
        "per_device_bytes": rep["per_device_bytes"],
        "vars": [{"name": r["name"], "role": r["role"], "spec": r["spec"],
                  "bytes": r["bytes"],
                  "per_device_bytes": r["per_device_bytes"]}
                 for r in rows[:max_vars]],
    }


# -- per-device telemetry ----------------------------------------------------


def device_memory_stats():
    """Per-device memory stats where the backend exposes them. Returns
    a list of ``{"id", "kind", "bytes_in_use", "peak_bytes_in_use",
    "bytes_limit"}`` (missing fields None — host CPU reports no stats at
    all, which yields all-None entries). Never forces backend creation:
    with no backend initialized it returns []."""
    try:
        import jax

        try:
            from jax._src import xla_bridge as _xb

            if hasattr(_xb, "_backends") and not _xb._backends:
                return []
        except ImportError:
            pass
        devs = jax.local_devices()
    except Exception:
        return []
    out = []
    for d in devs:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        out.append({
            "id": int(d.id), "kind": d.device_kind,
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        })
    return out


def update_device_gauges():
    """Sample per-device memory into ``obs.metrics`` gauges
    (``device.<id>.bytes_in_use`` / ``.peak_bytes_in_use``) and — when
    span tracing is enabled — per-device counter lanes in the Chrome
    trace. Returns ``(stats, high_water)`` where ``high_water`` is the
    device dict with the largest ``bytes_in_use`` (None when the
    backend reports nothing)."""
    from . import metrics as _metrics
    from . import trace as _trace

    stats = device_memory_stats()
    high = None
    for d in stats:
        if d["bytes_in_use"] is None:
            continue
        _metrics.gauge(f"device.{d['id']}.bytes_in_use").set(
            d["bytes_in_use"])
        if d["peak_bytes_in_use"] is not None:
            _metrics.gauge(f"device.{d['id']}.peak_bytes_in_use").set(
                d["peak_bytes_in_use"])
        if _trace.tracing_enabled():
            _trace.device_counter(d["id"], "bytes_in_use",
                                  d["bytes_in_use"],
                                  label=f"device {d['id']} ({d['kind']})")
        if high is None or d["bytes_in_use"] > high["bytes_in_use"]:
            high = d
    return stats, high


# -- executable-level profiling ----------------------------------------------


def profile_jit_fn(jit_fn, arg_structs, mesh=None):
    """Lower + compile ``jit_fn`` against ``arg_structs`` (shape/dtype
    structs, shardings preserved) and return its CollectiveProfile, or
    None when lowering fails. BLOCKING (pays an XLA compile): call off
    the step path only — the Executor path goes through the cached
    ``obs.mfu.entry_analysis`` instead."""
    try:
        # a hydrated/compiled fn (runtime.aot) has no .lower — profile
        # the actual executable's HLO directly
        c = jit_fn if not hasattr(jit_fn, "lower") \
            else jit_fn.lower(*arg_structs).compile()
        return collective_profile(c.as_text(), mesh=mesh)
    except Exception:
        return None
