"""Request-scoped distributed tracing: per-request timelines and
tail-latency attribution across the serve fleet.

The write side is already in the serving stack — every lifecycle edge
journals one ``req.*`` event under the established ACTIVE guard (one
``is not None`` check when the flight recorder is off):

- ``req.submit`` / ``req.rate_hold`` / ``req.dispatch`` /
  ``req.requeue`` — the router's journal (``<run_dir>/router``), on
  the router clock; the trace id is minted at ``Router.submit()`` and
  rides dispatch into the replica on BOTH pool modes (in-process call
  and the worker's newline-JSON protocol).
- ``req.admit`` / ``req.preempt`` / ``req.decode_mark`` — each
  replica's journal (``<run_dir>/rank_NN``), on the engine clock, plus
  the terminal ``request`` record carrying the engine-side phase
  fields (``queue_ms``/``prefill_ms``/``preempt_ms``/``decode_ms``).

This module is the READ side: :func:`assemble_run` joins those
journals by rid into per-request timelines (one dispatch segment per
replica incarnation — a requeued request carries BOTH the victim's
segment and the re-dispatched one's); :func:`attribute` decomposes
TTFT and e2e into exact phase contributions::

    rate_limit_wait + router_queue + requeue + sched_queue + prefill
        == TTFT
    TTFT + preempt + decode == e2e

``prefill_ms`` and ``decode_ms`` are computed as remainders of the
stamped phases, so the telescope sums to e2e by construction — under
``ManualClock`` (dyadic timestamps) every phase is ALSO bitwise equal
to its direct stamp difference, which the self-test fixtures assert
to the nanosecond. :func:`tail_report` ranks the worst-percentile
requests by TTFT/e2e and names where their time went;
:func:`request_lane_events` renders timelines as Perfetto slices on
pid=replica lanes with flow arrows across requeues (merged into the
fleet trace by ``obs.fleet.merge_chrome_traces(include_requests=
True)``). ``tools/request_report.py`` is the CLI front door.
"""
from __future__ import annotations

import json
import os

from . import trace as _trace

__all__ = [
    "PHASES", "REQUEST_TID_BASE",
    "assemble", "assemble_run", "attribute", "attribute_run",
    "attribution_sum", "tail_report", "request_lane_events",
    "write_request_trace",
]

# canonical attribution order: summed left-to-right these telescope to
# e2e_ms (prefill and decode are remainders — see module docstring)
PHASES = ("rate_limit_wait_ms", "router_queue_ms", "requeue_ms",
          "sched_queue_ms", "prefill_ms", "preempt_ms", "decode_ms")

# request lanes use tids far above any plausible thread ident's low
# bits mattering — one tid per request, shared across the pid lanes it
# visits, so Perfetto reads a requeued request as ONE named row that
# crosses replica lanes
REQUEST_TID_BASE = 1 << 21


def _new_timeline(rid):
    return {
        "rid": rid, "trace": None, "tenant": None, "state": None,
        "arrival_t": None, "admit_t": None, "first_token_t": None,
        "finish_t": None, "prompt_tokens": None, "output_tokens": None,
        "preemptions": 0, "replica": None, "cost": None,
        "rate_wait_ms": 0.0, "rate_holds": [],
        "dispatches": [], "requeues": [],
        "admits": [], "preempts": [], "decode_marks": [],
        "segments": [], "record": None,
    }


def assemble(router_run=None, rank_runs=None):
    """Join one run's journals into ``{rid: timeline}``.

    ``router_run`` is the router's :func:`obs.fleet.load_journal` dict
    (or None for a router-less single-engine run); ``rank_runs`` maps
    replica id -> loaded journal (a plain single-process journal
    passes as ``{None: run}`` — the record's own ``replica`` field
    labels the lane). Timelines are plain dicts; ``segments`` is the
    finalized per-incarnation list ``[{replica, start, end, seq,
    requeue_reason}]`` the lane export and the drill assertions read.
    """
    tls = {}

    def tl(rid):
        t = tls.get(rid)
        if t is None:
            t = tls[rid] = _new_timeline(rid)
        return t

    def ingest(e, replica):
        kind = e.get("kind")
        if not str(kind or "").startswith("req."):
            return
        if kind == "req.decode_mark":
            for rid in e.get("rids") or []:
                tl(rid)["decode_marks"].append({
                    "t": e.get("at"), "step": e.get("step"),
                    "replica": e.get("replica", replica)})
            return
        rid = e.get("rid")
        if rid is None:
            return
        t = tl(rid)
        if kind == "req.submit":
            t["arrival_t"] = e.get("at")
            t["tenant"] = e.get("tenant")
            t["trace"] = e.get("trace") or t["trace"]
            t["cost"] = e.get("cost")
            if t["prompt_tokens"] is None:
                t["prompt_tokens"] = e.get("prompt_tokens")
        elif kind == "req.rate_hold":
            t["rate_holds"].append(e.get("at"))
        elif kind == "req.dispatch":
            t["dispatches"].append({
                "t": e.get("at"), "replica": e.get("replica"),
                "seq": e.get("seq"),
                "rate_wait_ms": e.get("rate_wait_ms") or 0.0})
            t["trace"] = e.get("trace") or t["trace"]
        elif kind == "req.requeue":
            t["requeues"].append({
                "t": e.get("at"), "replica": e.get("replica"),
                "reason": e.get("reason")})
        elif kind == "req.admit":
            t["admits"].append({
                "t": e.get("at"), "resumed": bool(e.get("resumed")),
                "replica": replica})
        elif kind == "req.preempt":
            t["preempts"].append(e.get("at"))

    for e in (router_run or {}).get("events") or []:
        ingest(e, None)
    for replica, run in (rank_runs or {}).items():
        # a shared single-process journal (mode="local" with one
        # recorder) carries the router-side req.* events too — ingest
        # handles every kind, whichever journal it landed in
        for e in run.get("events") or []:
            ingest(e, replica)
        for rec in run.get("requests") or []:
            rid = rec.get("rid")
            if rid is None:
                continue
            t = tl(rid)
            old = t["record"]
            # the FINAL incarnation's record wins (a requeued request
            # may leave a cancelled torso in the victim's journal)
            if old is None or (rec.get("finish_t") or 0.0) >= \
                    (old.get("finish_t") or 0.0):
                t["record"] = rec

    for t in tls.values():
        _finalize(t)
    return tls


def _finalize(t):
    rec = t["record"]
    if rec is not None:
        for k in ("state", "admit_t", "first_token_t", "finish_t",
                  "output_tokens", "replica", "trace"):
            if rec.get(k) is not None:
                t[k] = rec[k]
        for k in ("arrival_t", "prompt_tokens"):
            # router stamps win (fleet truth); fill from the record
            # only for router-less runs
            if t[k] is None and rec.get(k) is not None:
                t[k] = rec[k]
        t["preemptions"] = int(rec.get("preemptions") or 0)
    t["dispatches"].sort(key=lambda d: (d["t"] is None, d["t"]))
    t["requeues"].sort(key=lambda r: (r["t"] is None, r["t"]))
    t["admits"].sort(key=lambda a: (a["t"] is None, a["t"]))
    t["preempts"] = sorted(x for x in t["preempts"] if x is not None)
    t["decode_marks"].sort(key=lambda m: (m["t"] is None, m["t"]))
    if t["dispatches"]:
        # rate_wait_ms is CUMULATIVE on each dispatch event: the last
        # dispatch carries the request's total rate-limit wait
        t["rate_wait_ms"] = float(
            t["dispatches"][-1]["rate_wait_ms"] or 0.0)
        for i, d in enumerate(t["dispatches"]):
            rq = t["requeues"][i] if i < len(t["requeues"]) else None
            t["segments"].append({
                "replica": d["replica"], "start": d["t"],
                "end": rq["t"] if rq is not None else t["finish_t"],
                "seq": d.get("seq") or (i + 1),
                "requeue_reason": rq["reason"] if rq is not None
                else None})
    elif t["admit_t"] is not None:
        # router-less single-engine run: one segment, admission to
        # finish, on the record's own replica lane
        t["segments"].append({
            "replica": t["replica"] if t["replica"] is not None else 0,
            "start": t["admit_t"], "end": t["finish_t"], "seq": 1,
            "requeue_reason": None})


def assemble_run(run_dir):
    """Assemble every request timeline under ``run_dir``: the router
    journal (``router/``) plus every ``rank_NN`` replica journal; a
    directory that IS a single journal (no rank subdirs) loads as one
    replica. Raises ``FileNotFoundError`` when no journal exists."""
    from . import fleet as _fleet

    rd = _fleet.router_dir(run_dir)
    router_run = _fleet.load_journal(rd) if rd else None
    ranks = _fleet.rank_dirs(run_dir)
    rank_runs = {r: _fleet.load_journal(p)
                 for r, p in sorted(ranks.items())}
    if router_run is None and not rank_runs:
        rank_runs = {None: _fleet.load_journal(run_dir)}
    return assemble(router_run, rank_runs)


# -- attribution -------------------------------------------------------------


def attribute(t):
    """Decompose one finished timeline's TTFT and e2e into the exact
    phase contributions (ms) of :data:`PHASES`. None when the request
    never produced a first token + finish (attribution needs both).

    ``rate_limit_wait`` is the router's closed tenant-bucket holds;
    ``router_queue`` is time enqueued at the router beyond that;
    ``requeue`` is time lost on dead replicas (dispatch -> requeue,
    per victim incarnation); ``sched_queue`` is the final replica's
    dispatch -> scheduler admission; ``preempt`` is the final
    incarnation's paired preempt/resume loss (an unpaired tail preempt
    closes at finish). ``prefill = TTFT - (the four queue phases)``
    and ``decode = e2e - TTFT - preempt`` are remainders, so summing
    :data:`PHASES` left-to-right reproduces ``e2e_ms`` exactly."""
    a, ft, f = t["arrival_t"], t["first_token_t"], t["finish_t"]
    if a is None or ft is None or f is None:
        return None
    ttft = (ft - a) * 1e3
    e2e = (f - a) * 1e3
    disp, rqs = t["dispatches"], t["requeues"]
    rate = float(t["rate_wait_ms"]) if disp else 0.0
    router_q = 0.0
    requeue = 0.0
    if disp:
        # dispatch i leaves the router queue it re-entered at the
        # previous requeue (arrival for the first)
        starts = [a] + [r["t"] for r in rqs[:len(disp) - 1]]
        router_q = sum(d["t"] - s for d, s in zip(disp, starts)) \
            * 1e3 - rate
        requeue = sum(r["t"] - d["t"] for d, r in zip(disp, rqs)) * 1e3
        last_d = disp[-1]["t"]
    else:
        last_d = a
    m = t["admit_t"]
    sched_q = (m - last_d) * 1e3 if m is not None else 0.0
    pre = rate + router_q + requeue + sched_q
    prefill = ttft - pre
    # preemption loss inside the FINAL incarnation only: a victim
    # incarnation's preempts are already inside requeue_ms
    pts = [p for p in t["preempts"] if p >= last_d]
    rts = sorted(adm["t"] for adm in t["admits"]
                 if adm["resumed"] and adm["t"] is not None
                 and adm["t"] >= last_d)
    preempt = 0.0
    for i, p in enumerate(pts):
        end = rts[i] if i < len(rts) else f
        preempt += (end - p) * 1e3
    decode = e2e - ttft - preempt
    return {
        "rid": t["rid"], "trace": t["trace"], "tenant": t["tenant"],
        "state": t["state"],
        "replicas": [s["replica"] for s in t["segments"]],
        "dispatches": len(disp), "requeues": len(rqs),
        "preemptions": t["preemptions"],
        "ttft_ms": ttft, "e2e_ms": e2e,
        "rate_limit_wait_ms": rate, "router_queue_ms": router_q,
        "requeue_ms": requeue, "sched_queue_ms": sched_q,
        "prefill_ms": prefill, "preempt_ms": preempt,
        "decode_ms": decode,
    }


def attribute_run(timelines):
    """Every attributable timeline's decomposition, rid-sorted."""
    out = []
    for rid in sorted(timelines):
        att = attribute(timelines[rid])
        if att is not None:
            out.append(att)
    return out


def attribution_sum(att):
    """The canonical left-to-right phase sum — equals ``att["e2e_ms"]``
    exactly under ``ManualClock`` (the self-test invariant)."""
    s = 0.0
    for k in PHASES:
        s += att[k]
    return s


def tail_report(timelines, key="ttft_ms", pct=99.0, k=None):
    """Tail-latency attribution: the worst requests by ``key``
    (``ttft_ms`` or ``e2e_ms``) with their phase decompositions, plus
    fleet-wide phase totals/shares. ``k`` picks the K worst outright;
    otherwise every request at or above the exact ``pct`` percentile
    of ``key`` makes the list. None when nothing is attributable."""
    from .metrics import exact_percentile

    atts = attribute_run(timelines)
    if not atts:
        return None
    ranked = sorted(atts, key=lambda x: (-x[key], x["rid"]))
    if k is not None:
        worst = ranked[:max(0, int(k))]
        threshold = None
    else:
        threshold = exact_percentile([x[key] for x in atts], pct)
        worst = [x for x in ranked if x[key] >= threshold]
    totals = {p: 0.0 for p in PHASES}
    for x in atts:
        for p in PHASES:
            totals[p] += x[p]
    grand = sum(totals.values())
    shares = {p: (totals[p] / grand if grand > 0 else 0.0)
              for p in PHASES}
    return {"requests": len(atts), "key": key, "pct": pct, "k": k,
            "threshold": threshold, "worst": worst,
            "phase_totals_ms": totals, "phase_share": shares}


# -- Perfetto request lanes --------------------------------------------------


def request_lane_events(timelines, t0=None):
    """Render timelines as Chrome-trace events: one "X" slice per
    dispatch segment on ``pid=replica``, one tid per request (shared
    across lanes), and an "s"/"f" flow pair across every requeue — the
    arrow Perfetto draws from the victim replica's lane to the
    re-dispatched one's. ``t0`` anchors the time origin (defaults to
    the earliest segment start); timelines without segments are
    skipped. Thread-name metas label each request row."""
    tls = [timelines[rid] for rid in sorted(timelines)
           if timelines[rid]["segments"]]
    tls = [t for t in tls
           if any(s["start"] is not None for s in t["segments"])]
    if not tls:
        return []
    if t0 is None:
        t0 = min(s["start"] for t in tls for s in t["segments"]
                 if s["start"] is not None)
    events = []
    threads = {}
    flow_id = 0
    for idx, t in enumerate(tls):
        tid = REQUEST_TID_BASE + idx
        name = f"req {t['rid']}"
        prev = None
        for seg in t["segments"]:
            if seg["start"] is None:
                continue
            pid = seg["replica"] if seg["replica"] is not None else 0
            ts_us = (seg["start"] - t0) * 1e6
            end = seg["end"] if seg["end"] is not None else seg["start"]
            events.append({
                "ph": "X", "pid": pid, "tid": tid, "name": name,
                "cat": "req", "ts": ts_us,
                "dur": max(0.0, (end - seg["start"]) * 1e6),
                "args": {"rid": t["rid"], "trace": t["trace"],
                         "tenant": t["tenant"], "seq": seg["seq"],
                         "state": t["state"],
                         "requeue_reason": seg["requeue_reason"]}})
            threads[(pid, tid)] = name
            if prev is not None:
                # the requeue crossing: tail on the victim lane at the
                # segment's end, head on the new lane at re-dispatch
                flow_id += 1
                prev_pid, prev_end_us = prev
                events.append(_trace.flow_start(
                    name, flow_id, prev_pid, tid, prev_end_us,
                    rid=t["rid"]))
                events.append(_trace.flow_finish(
                    name, flow_id, pid, tid, ts_us, rid=t["rid"]))
            prev = (pid, (end - t0) * 1e6)
    for (pid, tid), name in sorted(threads.items()):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": name}})
    return events


def write_request_trace(timelines, path):
    """Standalone Perfetto export of the request lanes (the merged
    fleet trace embeds the same events via ``obs.fleet.
    merge_chrome_traces(include_requests=True)``). Returns ``{events,
    slices, path}``."""
    events = request_lane_events(timelines)
    for pid in sorted({e["pid"] for e in events}):
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": f"replica {pid}"}})
        events.append({"ph": "M", "pid": pid,
                       "name": "process_sort_index",
                       "args": {"sort_index": pid}})
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                  default=str)
    return {"events": len(events),
            "slices": sum(1 for e in events if e["ph"] == "X"),
            "path": path}
