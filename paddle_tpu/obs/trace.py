"""Wall-time span tracer with Chrome-trace export.

``span("executor.compile", uid=3)`` records one complete event into a
bounded in-memory ring buffer; ``export_chrome_trace(path)`` dumps the
buffer as ``chrome://tracing`` / Perfetto JSON. This is the host-side
timeline complement to ``jax.profiler`` (which owns the device/XLA view,
see ``utils/profiler.py``): compiles, runs, dataloader waits, checkpoint
writes — the step-time attribution the MLPerf TPU scaling work builds
its analysis on.

Off by default. ``span()`` with tracing disabled returns one shared
no-op context manager — no allocation, no clock read, one module-bool
check (the same discipline as the ``resilience.inject`` ``if ACTIVE``
hooks). Opt in per process with env ``PADDLE_TPU_TRACE=1`` or at runtime
with ``enable_tracing()``.

The ring buffer is bounded (default 65536 spans): a week-long serving
process can leave tracing on and the newest spans win.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

__all__ = [
    "span", "enable_tracing", "disable_tracing", "tracing_enabled",
    "clear_trace", "trace_events", "export_chrome_trace",
    "device_counter", "set_rank", "current_rank",
    "flow_start", "flow_finish",
    "DEFAULT_CAPACITY", "DEVICE_PID_BASE", "RANK_PID_STRIDE",
]

DEFAULT_CAPACITY = 65536
# per-device lanes render as separate Chrome-trace processes; their pids
# are offset far above any real host pid so they never collide with the
# host lane
DEVICE_PID_BASE = 1 << 20
# per-RANK namespace inside the device pid band: rank r's device d lane
# is DEVICE_PID_BASE + r * RANK_PID_STRIDE + d, so a merged fleet trace
# (obs.fleet.merge_chrome_traces) never interleaves two ranks' device
# counter lanes under one pid. 4096 devices per process is far above
# any real per-host device count
RANK_PID_STRIDE = 1 << 12

# this process's rank identity (multi-process gangs: the supervisor
# hands each worker PADDLE_TPU_RANK). None = single-process, exports
# keep the historical os.getpid()/DEVICE_PID_BASE+id lanes exactly
_rank = None


def set_rank(rank):
    """Adopt a rank identity for trace exports: host spans land on
    pid=rank (a stable lane a merged fleet trace can line up, unlike
    OS pids that recycle across elastic relaunches) and device counter
    lanes shift into the rank's namespace slice."""
    global _rank
    _rank = None if rank is None else int(rank)


def current_rank():
    return _rank

_enabled = False
_events: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)
# per-device counter samples (obs.spmd.update_device_gauges feeds this):
# (device_id, name, ts µs, value); bounded like the span ring
_device_samples: collections.deque = collections.deque(maxlen=16384)
_device_labels: dict = {}  # device_id -> lane label for the trace meta
# one perf-counter epoch per process: every span's ts is an offset from
# here, so spans from different threads land on one comparable timeline
_EPOCH = time.perf_counter()

_NULL = contextlib.nullcontext()  # stateless + reentrant: safe to share


class _Span:
    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        # deque.append with maxlen is atomic under the GIL: no lock on
        # the record path
        _events.append((self.name,
                        (self._t0 - _EPOCH) * 1e6,  # ts µs
                        (t1 - self._t0) * 1e6,      # dur µs
                        threading.get_ident(),
                        self.attrs))
        return False


def span(name, **attrs):
    """Context manager timing one named span. A no-op (shared null
    context) unless tracing is enabled."""
    if not _enabled:
        return _NULL
    return _Span(name, attrs)


def enable_tracing(capacity=None):
    """Turn span recording on; ``capacity`` resizes (and clears) the
    ring buffer."""
    global _enabled, _events
    if capacity is not None and capacity != _events.maxlen:
        _events = collections.deque(maxlen=int(capacity))
    _enabled = True


def disable_tracing():
    """Stop recording; already-recorded spans stay exportable."""
    global _enabled
    _enabled = False


def tracing_enabled():
    return _enabled


def clear_trace():
    _events.clear()
    _device_samples.clear()
    _device_labels.clear()


def device_counter(device_id, name, value, label=None):
    """Record one per-device counter sample (e.g. HBM bytes in use) for
    the Chrome trace's per-device pid lanes. A no-op when tracing is
    disabled — callers on a hot path should gate on
    ``tracing_enabled()`` themselves (``obs.spmd.update_device_gauges``
    does)."""
    if not _enabled:
        return
    if label is not None:
        _device_labels[int(device_id)] = label
    _device_samples.append((int(device_id), name,
                            (time.perf_counter() - _EPOCH) * 1e6,
                            float(value)))


def flow_start(name, flow_id, pid, tid, ts_us, **args):
    """One Chrome-trace flow-start event ("s"): the tail of an arrow
    Perfetto draws between two slices — possibly on different pid
    lanes. Pair with :func:`flow_finish` under the same ``flow_id``
    (``obs.reqtrace`` uses these to draw a requeued request crossing
    from the victim replica's lane to the re-dispatched one's)."""
    return {"ph": "s", "cat": "req", "name": str(name),
            "id": int(flow_id), "pid": pid, "tid": tid,
            "ts": float(ts_us), "args": dict(args)}


def flow_finish(name, flow_id, pid, tid, ts_us, **args):
    """The matching flow-finish ("f") for :func:`flow_start`.
    ``bp="e"`` binds the arrowhead to the ENCLOSING slice at this
    timestamp rather than the next slice to start — the binding that
    keeps the arrow on the re-dispatch segment itself."""
    return {"ph": "f", "bp": "e", "cat": "req", "name": str(name),
            "id": int(flow_id), "pid": pid, "tid": tid,
            "ts": float(ts_us), "args": dict(args)}


def trace_events():
    """Snapshot of recorded spans as dicts (newest-capped by the ring)."""
    return [{"name": n, "ts": ts, "dur": dur, "tid": tid, "args": attrs}
            for n, ts, dur, tid, attrs in list(_events)]


def export_chrome_trace(path):
    """Write the span buffer as Chrome trace-event JSON (load in
    chrome://tracing or https://ui.perfetto.dev). Returns the number of
    spans exported. With a rank identity set (:func:`set_rank` / env
    ``PADDLE_TPU_RANK``) the host lane is pid=rank and device lanes are
    rank-namespaced, so per-rank exports fuse collision-free."""
    rank = _rank
    pid = os.getpid() if rank is None else rank
    host_name = "paddle_tpu" if rank is None \
        else f"paddle_tpu rank {rank:02d}"
    events = [{"ph": "X", "pid": pid, "tid": tid, "name": n,
               "ts": ts, "dur": dur, "args": attrs}
              for n, ts, dur, tid, attrs in list(_events)]
    events.append({"ph": "M", "pid": pid, "name": "process_name",
                   "args": {"name": host_name}})
    # per-device pid lanes: counter samples (HBM gauges) render as one
    # Chrome-trace "process" per device, below the host span lane —
    # inside this rank's namespace slice of the device pid band
    dev_base = DEVICE_PID_BASE + (rank or 0) * RANK_PID_STRIDE
    lanes = set()
    for dev_id, name, ts, value in list(_device_samples):
        lane = dev_base + dev_id
        lanes.add((lane, dev_id))
        events.append({"ph": "C", "pid": lane, "name": name, "ts": ts,
                       "args": {"value": value}})
    for lane, dev_id in sorted(lanes):
        label = _device_labels.get(dev_id, f"device {dev_id}")
        if rank is not None:
            label = f"rank {rank:02d} {label}"
        events.append({"ph": "M", "pid": lane, "name": "process_name",
                       "args": {"name": label}})
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        # default=str: span attrs may carry shapes/dtypes/paths — never
        # let an exotic attr make the whole export unserializable
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                  default=str)
    return sum(1 for e in events if e["ph"] == "X")


if os.environ.get("PADDLE_TPU_TRACE", "").lower() not in ("", "0", "false"):
    enable_tracing()

# a supervised gang worker inherits its rank from the launcher
# (GangSupervisor / dist.launch hand each worker PADDLE_TPU_RANK)
try:
    set_rank(int(os.environ["PADDLE_TPU_RANK"]))
except (KeyError, ValueError):
    pass
