"""MFU / goodput accounting: turn step timings into utilization numbers.

The MLPerf TPU-pod scaling work and the Gemma-on-Cloud-TPU comparisons
treat three numbers as table stakes for operating a training stack:
step time, model-FLOPs-utilization (achieved FLOP/s over the chip's
peak), and goodput (how much of the wall clock went into steps that
actually advanced the model). The reference keeps these in scattered
VLOG output; here they are a small accounting layer the run journal
(``obs.journal``) feeds and summarizes.

FLOPs come from XLA's own ``cost_analysis`` on the compiled executable
(via ``utils.stats.compiled_stats``), cached per Executor cache entry —
no analytical per-layer formula to drift out of date. Peak FLOP/s is
configurable (``set_peak_flops`` / env ``PADDLE_TPU_PEAK_FLOPS``) with a
built-in per-chip bf16 table; on backends with no known peak (host CPU)
MFU is reported as ``None`` rather than a made-up number.
"""
from __future__ import annotations

import os
import threading

__all__ = [
    "PEAK_FLOPS_BY_KIND", "peak_flops", "set_peak_flops",
    "executable_flops", "entry_flops", "entry_flops_nowait",
    "entry_analysis", "entry_analysis_nowait", "MFUAccounting", "goodput",
]

# per-chip peak bf16 FLOP/s (the denominators bench.py uses)
PEAK_FLOPS_BY_KIND = {
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6e": 918e12,
}

_peak_override = None


def set_peak_flops(value):
    """Pin the peak FLOP/s used for MFU (``None`` reverts to
    autodetect). Env ``PADDLE_TPU_PEAK_FLOPS`` does the same per
    process."""
    global _peak_override
    _peak_override = float(value) if value is not None else None


def peak_flops():
    """Peak FLOP/s for MFU: explicit ``set_peak_flops`` wins, then env
    ``PADDLE_TPU_PEAK_FLOPS``, then the per-chip table keyed on the
    backend's device kind. ``None`` when nothing is known (host CPU) —
    the journal then reports achieved FLOP/s without an MFU ratio."""
    if _peak_override is not None:
        return _peak_override
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        try:
            from jax._src import xla_bridge as _xb

            if hasattr(_xb, "_backends") and not _xb._backends:
                # never force backend creation for a ratio: this runs
                # from RunJournal.close() at atexit, where probing
                # jax.devices() could pin a platform (or block on a
                # wedged TPU tunnel) as an exit side effect
                return None
        except ImportError:
            pass
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for k, v in PEAK_FLOPS_BY_KIND.items():
        if k.lower() in kind.lower():
            return v
    return None


def executable_flops(fn, *example_args):
    """FLOPs of one invocation of ``fn`` per XLA's cost analysis, or
    ``None`` when the backend doesn't report it."""
    from ..utils.stats import compiled_stats

    try:
        cost = compiled_stats(fn, *example_args)["cost"]
    except Exception:
        return None
    v = cost.get("flops")
    return float(v) if v else None


def entry_analysis(compiled):
    """Lazy memory/cost/collective attribution for one Executor cache
    entry (``static_/executor.py`` ``_Compiled``). Lowers the entry's
    jitted fn against the arg structs captured at build time and reads
    XLA's ``memory_analysis`` / ``cost_analysis`` plus the executable's
    HLO text for the CollectiveProfile (``obs.spmd``); the result
    (fields possibly None when the backend reports nothing) is cached
    on the entry so the compile cost is paid once."""
    cached = getattr(compiled, "_entry_analysis", None)
    if cached is not None:
        return cached
    out = {"memory": None, "cost": None, "collectives": None}
    structs = getattr(compiled, "arg_structs", None)
    if structs is not None:
        from ..utils.stats import _analysis_dict, _cost_dict

        try:
            # an AOT-hydrated entry's fn IS already a jax.stages.
            # Compiled (runtime.aot) — analyze the actual executable
            # instead of paying a re-lower+compile
            c = compiled.fn if not hasattr(compiled.fn, "lower") \
                else compiled.fn.lower(*structs).compile()
        except Exception:
            c = None
        if c is not None:
            try:
                ma = c.memory_analysis()
                if ma is not None:
                    mem = _analysis_dict(ma, (
                        "argument_size_in_bytes", "output_size_in_bytes",
                        "temp_size_in_bytes", "alias_size_in_bytes",
                        "generated_code_size_in_bytes"))
                    out["memory"] = mem or None
            except Exception:
                pass
            try:
                cost = _cost_dict(c.cost_analysis())
                out["cost"] = cost or None
            except Exception:
                pass
            try:
                from . import spmd as _spmd

                mesh = None
                axes = getattr(compiled, "mesh_axes", None)
                if axes is not None:
                    mesh = (axes, getattr(compiled, "mesh_device_ids",
                                          None))
                out["collectives"] = _spmd.collective_profile(
                    c.as_text(), mesh=mesh)
            except Exception:
                pass
    compiled._entry_analysis = out
    return out


def entry_flops(compiled):
    """FLOPs per run of one Executor cache entry (lazy, cached), or
    ``None``. BLOCKING: may pay the entry's analysis compile — fine for
    ``cache_stats(per_entry=True)``, never call it on the step path."""
    cost = entry_analysis(compiled)["cost"]
    v = (cost or {}).get("flops")
    return float(v) if v else None


_pending_lock = threading.Lock()
_pending_threads: list = []
_shutting_down = False


def _drain_analysis_threads(timeout_s=5.0):
    """Interpreter-exit guard for the background analysis compiles: a
    daemon thread still INSIDE an XLA compilation when Python
    finalizes tears down the C++ compile thread pool under it —
    ``terminate called without an active exception``, SIGABRT — which
    turns a clean worker exit into a spurious crash (a supervised gang
    would burn a restart on it). Refuse new analyses and give in-flight
    ones a bounded window to land; short-lived journaled processes (CI
    drills, preempted workers) exit clean, and a multi-second real-TPU
    compile still can't stall a preemption exit past the budget."""
    import time

    global _shutting_down
    _shutting_down = True
    deadline = time.monotonic() + float(timeout_s)
    with _pending_lock:
        threads = list(_pending_threads)
    for t in threads:
        try:
            t.join(max(0.0, deadline - time.monotonic()))
        except RuntimeError:
            pass  # never-started thread (start() itself failed)


def _analysis_worker(compiled):
    try:
        if not _shutting_down:
            entry_analysis(compiled)
    finally:
        with _pending_lock:
            if threading.current_thread() in _pending_threads:
                _pending_threads.remove(threading.current_thread())


def entry_analysis_nowait(compiled):
    """Non-blocking ``entry_analysis`` for the journal's step path:
    returns the cached analysis dict when it has landed, otherwise
    kicks the lower+compile off ONCE in a daemon thread and returns
    None — the step path must never stall behind a second XLA
    compilation (tens of seconds on a real chip). Early steps of each
    entry simply carry no flops/comm attribution; the MFU accounting
    already scopes achieved-FLOP/s to the steps that do. In-flight
    threads are drained at interpreter exit (see
    :func:`_drain_analysis_threads`)."""
    cached = getattr(compiled, "_entry_analysis", None)
    if cached is not None:
        return cached
    if _shutting_down:
        return None
    with _pending_lock:
        if getattr(compiled, "_entry_analysis_pending", False):
            return None
        compiled._entry_analysis_pending = True
        t = threading.Thread(target=_analysis_worker, args=(compiled,),
                             daemon=True)
        _pending_threads.append(t)
    t.start()
    return None


import atexit  # noqa: E402  (registration belongs next to the hook)

atexit.register(_drain_analysis_threads)


def entry_flops_nowait(compiled):
    """Non-blocking FLOPs for one entry (see
    ``entry_analysis_nowait``); None until the analysis lands."""
    cached = entry_analysis_nowait(compiled)
    if cached is None:
        return None
    return float((cached["cost"] or {}).get("flops") or 0) or None


def goodput(productive, skipped=0, retried=0):
    """Fraction of attempted step work that advanced the model:
    ``productive / (productive + skipped + retried)``. Skipped steps
    (nonfinite discard/rollback) and transient retries both burned a
    step's wall time without contributing. ``None`` with no steps."""
    total = productive + skipped + retried
    if total <= 0:
        return None
    return productive / float(total)


class MFUAccounting:
    """Accumulates per-step (step_ms, flops, examples) and renders the
    run-level summary: achieved FLOP/s, MFU vs the configured peak, and
    goodput from productive/skipped/retried counts."""

    def __init__(self, peak=None):
        self._peak = peak
        self.productive = 0
        self.skipped = 0
        self.retried = 0
        self._timed_ms = 0.0
        self._timed_steps = 0
        self._flop_ms = 0.0   # step_ms summed only where flops known
        self._flops = 0.0
        self._examples = 0
        self._comm_bytes = 0.0  # collective payload, steps where known
        self._wire_bytes = 0.0
        self._comm_steps = 0
        self._comm_flops = 0.0  # flops summed on comm-attributed steps

    def record(self, step_ms=None, flops=None, examples=None,
               productive=True, comm_bytes=None, wire_bytes=None,
               weight=1):
        """``weight`` is the number of optimizer steps this record
        covers — 1 normally, K for a fused ``run_steps`` window (whose
        step_ms/flops/examples/comm already describe the whole window,
        so only the step COUNTS need the weight)."""
        weight = max(1, int(weight))
        if productive:
            self.productive += weight
        else:
            self.skipped += weight
        if step_ms is not None and step_ms > 0:
            self._timed_ms += step_ms
            self._timed_steps += 1
            if flops:
                self._flops += float(flops)
                self._flop_ms += step_ms
        if comm_bytes:
            self._comm_bytes += float(comm_bytes)
            self._wire_bytes += float(wire_bytes or comm_bytes)
            self._comm_steps += 1
            if flops:
                self._comm_flops += float(flops)
        if examples:
            self._examples += int(examples)

    def note_retry(self, n=1):
        self.retried += n

    def reclassify_skip(self):
        """A step already recorded as productive turned out discarded
        (the static guard detects nonfinite AFTER the executor's step
        record): move one step from productive to skipped."""
        if self.productive > 0:
            self.productive -= 1
            self.skipped += 1

    def summary(self):
        peak = self._peak if self._peak is not None else peak_flops()
        achieved = (self._flops / (self._flop_ms / 1e3)
                    if self._flop_ms > 0 else None)
        out = {
            "productive_steps": self.productive,
            "skipped_steps": self.skipped,
            "retries": self.retried,
            "goodput": goodput(self.productive, self.skipped, self.retried),
            "mean_step_ms": (self._timed_ms / self._timed_steps
                             if self._timed_steps else None),
            "achieved_flops_per_s": achieved,
            "peak_flops_per_s": peak,
            "mfu": (achieved / peak if achieved and peak else None),
        }
        if self._examples and self._timed_ms > 0:
            out["examples_per_s"] = self._examples / (self._timed_ms / 1e3)
        if self._comm_steps:
            # compute-vs-comm roofline over the comm-attributed steps
            # (obs.spmd): None fields when no ICI bandwidth is known
            from .spmd import comm_roofline

            out["comm_bytes_per_step"] = self._comm_bytes / self._comm_steps
            rl = comm_roofline(
                {"total_bytes": self._comm_bytes / self._comm_steps,
                 "wire_bytes": self._wire_bytes / self._comm_steps},
                flops=(self._comm_flops / self._comm_steps
                       if self._comm_flops else None),
                peak=peak)
            out["comm_share"] = rl["comm_share"]
            out["comm_bound"] = rl["bound"]
        return out
