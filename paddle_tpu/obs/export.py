"""Live SLO export: the metrics registry + serving/fleet SLO gauges as
Prometheus text, over a localhost HTTP endpoint and/or an atomic
textfile.

The journal (``obs.journal``) and the fleet aggregator (``obs.fleet``)
are post-hoc readers; a router or autoscaler needs the SAME signals
LIVE — queue depth, running count, TTFT/TPOT percentiles, per-rank
heartbeat age (ROADMAP item 5's scale-up/down inputs, and the
TTFT/TPOT/throughput axes the Gemma TPU serving comparison, arXiv
2605.25645, is framed in). This module is that signal plane:

- :func:`prometheus_text` — one Prometheus text-format snapshot:
  every ``obs.metrics`` instrument (counters/gauges/histograms with
  cumulative ``_bucket`` series) plus derived SLO gauges.
- SLO gauges per serve replica (``ServeEngine.stats()`` — the EXACT
  per-instance percentiles, labelled ``replica="N"``) and per rank
  (``paddle_tpu_rank_heartbeat_age_seconds`` from the rank journals'
  last flush under a fleet run dir).
- :class:`MetricsExporter` — ``GET /metrics`` on a localhost HTTP
  endpoint (``port=0`` picks an ephemeral port), and
  :func:`write_textfile` for node-exporter-style textfile collection
  (tmp + atomic rename: a scraper never reads a torn file).
- The multi-process path: ``live_engines()`` only ever discovers THIS
  process's replicas, so a fleet front-end composes
  :func:`router_lines` (``serving.fleet.Router`` truth, bitwise) with
  :func:`scrape` + :func:`merge_expositions` over each worker
  replica's own exporter (URL or textfile) — one exposition covering
  out-of-process replicas, which is what the autoscaler consumes.

Pull-only by design: nothing here runs on a step path, nothing ticks
unless scraped — the zero-overhead hook contract holds trivially.
Engines register themselves at construction (``serving.engine``'s
process-wide weak registry), so ``MetricsExporter()`` with no
arguments exports every live replica in the process.
"""
from __future__ import annotations

import math
import os
import re
import threading

from . import metrics as _metrics
from .metrics import Counter, Gauge, Histogram

__all__ = [
    "prometheus_text", "registry_lines", "slo_lines", "router_lines",
    "tenant_lines", "slo_engine_lines", "statusz_data",
    "render_statusz_html", "write_textfile", "parse_prometheus_text",
    "scrape", "merge_expositions", "MetricsExporter", "PREFIX",
]

PREFIX = "paddle_tpu_"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(name):
    return PREFIX + _NAME_RE.sub("_", str(name))


def _fmt(v):
    """Prometheus sample value. ``repr(float)`` is the shortest
    round-trip form, so a scraped value parses back to EXACTLY the
    source float — the property the exporter's acceptance gate
    (scraped TTFT/TPOT == ``ServeEngine.stats()``) rests on."""
    if v is None:
        return "NaN"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


class _Lines:
    """Ordered exposition lines with one ``# TYPE`` declaration per
    metric family (Prometheus rejects duplicates)."""

    def __init__(self):
        self.lines = []
        self._declared = set()

    def add(self, family, typ, value, labels=None):
        if family not in self._declared:
            self._declared.add(family)
            self.lines.append(f"# TYPE {family} {typ}")
        lbl = ""
        if labels:
            lbl = "{" + ",".join(
                f'{k}="{v}"' for k, v in labels.items()) + "}"
        self.lines.append(f"{family}{lbl} {_fmt(value)}")

    def raw(self, line):
        self.lines.append(line)


def registry_lines(registry=None):
    """Every ``obs.metrics`` instrument as Prometheus lines: counters
    and gauges verbatim, histograms as cumulative ``_bucket{le=...}``
    series + ``_sum``/``_count`` (the native Prometheus histogram
    shape, so server-side ``histogram_quantile`` works)."""
    reg = registry if registry is not None else _metrics.REGISTRY
    out = _Lines()
    for name in reg.names():
        inst = reg.get(name)
        n = _name(name)
        if isinstance(inst, Counter):
            out.add(n, "counter", inst.value)
        elif isinstance(inst, Histogram):
            buckets, counts, count, total = inst.bucket_counts()
            out.raw(f"# TYPE {n} histogram")
            cum = 0
            for b, c in zip(buckets, counts):
                cum += c
                out.raw(f'{n}_bucket{{le="{_fmt(b)}"}} {cum}')
            out.raw(f'{n}_bucket{{le="+Inf"}} {count}')
            out.raw(f"{n}_sum {_fmt(total)}")
            out.raw(f"{n}_count {count}")
        elif isinstance(inst, Gauge):
            out.add(n, "gauge", inst.value)
    return out.lines


def slo_lines(engines=None, run_dir=None, now=None):
    """Derived SLO gauges: per serve replica (queue depth, running,
    finished, exact TTFT/TPOT/e2e p50/p99 from that engine's OWN
    finished requests, KV-pool occupancy) and per rank (journal
    heartbeat age under a fleet ``run_dir``). ``engines=None``
    discovers every live ``ServeEngine`` in the process."""
    if engines is None:
        try:
            from ..serving.engine import live_engines

            engines = live_engines()
        except Exception:
            engines = []
    out = _Lines()
    s = PREFIX + "serving_slo_"
    for i, eng in enumerate(engines):
        rep = str(getattr(eng, "replica_id", i))
        try:
            st = eng.stats()
        except Exception:
            continue
        lbl = {"replica": rep}
        out.add(s + "queue_depth", "gauge", st.get("queue_depth"), lbl)
        out.add(s + "running", "gauge", st.get("running"), lbl)
        out.add(s + "finished", "gauge", st.get("finished"), lbl)
        out.add(s + "preemptions", "gauge", st.get("preemptions"), lbl)
        kv = st.get("kv") or {}
        if kv:
            out.add(s + "kv_used_pages", "gauge",
                    kv.get("used_pages"), lbl)
            out.add(s + "kv_utilization", "gauge",
                    kv.get("utilization"), lbl)
        for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
            d = st.get(key)
            if not d:
                continue
            for q in ("p50", "p99"):
                out.add(s + key, "gauge", d.get(q),
                        {"replica": rep, "q": q})
            out.add(s + key + "_count", "gauge", d.get("count"), lbl)
        # reqtrace phase attribution: where this replica's request
        # time went, as shares of total (queue + prefill + preempt +
        # decode over its finished requests) — the signal that says
        # whether a p99 breach is queueing or compute
        ph = st.get("phase_ms") or {}
        total = sum(v for v in ph.values()
                    if isinstance(v, (int, float)))
        if ph and total > 0:
            for phase in sorted(ph):
                out.add(s + "phase_share", "gauge",
                        float(ph[phase]) / total,
                        {"replica": rep, "phase": phase})
    if run_dir:
        from . import fleet as _fleet

        for rank, age in _fleet.heartbeat_ages(run_dir,
                                               now=now).items():
            out.add(PREFIX + "rank_heartbeat_age_seconds", "gauge",
                    age, {"rank": str(rank)})
    return out.lines


def router_lines(router):
    """The serve-fleet router's truth (``serving.fleet.Router.stats()``)
    as ``paddle_tpu_fleet_router_*`` gauges. Values are emitted in
    ``repr`` round-trip form like everything else here, so a scraped
    gauge parses back BITWISE equal to the stats dict — the router
    acceptance gate."""
    st = router.stats()
    out = _Lines()
    r = PREFIX + "fleet_router_"
    for key in ("queue_depth", "inflight", "dispatched", "requeued",
                "rejected", "completed", "replicas", "scale_ups",
                "scale_downs"):
        out.add(r + key, "gauge", st.get(key))
    for rep, d in sorted((st.get("per_replica") or {}).items()):
        lbl = {"replica": str(rep)}
        out.add(r + "outstanding_tokens", "gauge",
                d.get("outstanding_tokens"), lbl)
        out.add(r + "replica_inflight", "gauge", d.get("inflight"),
                lbl)
    for tenant, d in sorted((st.get("tenants") or {}).items()):
        lbl = {"tenant": str(tenant)}
        out.add(r + "tenant_served_tokens", "gauge",
                d.get("served_tokens"), lbl)
        out.add(r + "tenant_share", "gauge", d.get("share"), lbl)
        out.add(r + "tenant_queued", "gauge", d.get("queued"), lbl)
    for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
        d = st.get(key)
        if not d:
            continue
        for q in ("p50", "p99"):
            out.add(r + key, "gauge", d.get(q), {"q": q})
        out.add(r + key + "_count", "gauge", d.get("count"))
    return out.lines


def tenant_lines(router=None, engines=None):
    """The per-tenant chargeback plane (``obs.usage``) as labeled
    ``paddle_tpu_tenant_*{tenant="..."}`` gauges, in ``repr``
    round-trip form like everything else here — a scraped gauge parses
    back BITWISE equal to the rollup float. Merge-safe across
    replicas: router-level families carry only the tenant label and
    are emitted by exactly one router; engine-level families
    (``tenant_replica_*``) carry a distinguishing ``replica`` label,
    so :func:`merge_expositions` passes every series through verbatim
    (never sums two sources into one key)."""
    from . import usage as _usage

    out = _Lines()
    t = PREFIX + "tenant_"
    if router is not None:
        tu = _usage.router_tenant_usage(router)
        for tenant, d in sorted(tu["tenants"].items()):
            lbl = {"tenant": str(tenant)}
            for key in ("weight", "weight_share", "served_tokens",
                        "share", "queued", "requests", "completed",
                        "cancelled", "rejected", "rate_holds",
                        "requeued", "preemptions", "prompt_tokens",
                        "decode_tokens"):
                out.add(t + key, "gauge", d.get(key, 0), lbl)
            for key in ("queue_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
                for q in ("p50", "p99"):
                    v = d.get(f"{key}_{q}")
                    if v is not None:
                        out.add(t + key, "gauge", v,
                                {"tenant": str(tenant), "q": q})
    for i, eng in enumerate(engines or ()):
        try:
            eu = _usage.engine_tenant_usage(eng)
        except Exception:
            continue
        rep = str(eu.get("replica", i))
        rlbl = {"replica": rep}
        out.add(t + "replica_busy_ns", "gauge", eu["busy_ns"], rlbl)
        out.add(t + "replica_page_open", "gauge", eu["page_open"],
                rlbl)
        out.add(t + "replica_page_bytes", "gauge", eu["page_bytes"],
                rlbl)
        for tenant, d in sorted(eu["tenants"].items()):
            lbl = {"tenant": str(tenant), "replica": rep}
            for key in ("device_ns", "page_ns", "prompt_tokens",
                        "decode_tokens", "completed", "preemptions"):
                out.add(t + "replica_" + key, "gauge", d.get(key, 0),
                        lbl)
    return out.lines


def slo_engine_lines(evaluator):
    """The live SLO engine's truth (``obs.slo.SLOEvaluator``) as
    gauges: per-objective ``slo_burn_rate{objective=,window=}``,
    ``slo_budget_remaining{objective=}`` and
    ``slo_alert_active{objective=,severity=}``. Values are emitted in
    ``repr`` round-trip form like everything else here, so a scraped
    burn rate parses back BITWISE equal to the evaluator's float — the
    ISSUE-19 acceptance gate an alertmanager rule rests on."""
    out = _Lines()
    s = PREFIX + "slo_"
    for spec in evaluator.specs:
        obj = spec.name
        for label in evaluator.windows:
            v = evaluator.burn.get((obj, label))
            if v is None:
                continue
            out.add(s + "burn_rate", "gauge", v,
                    {"objective": obj, "window": label})
        rem = evaluator.budget_left.get(obj)
        if rem is not None:
            out.add(s + "budget_remaining", "gauge", rem,
                    {"objective": obj})
        out.add(s + "target", "gauge", spec.target,
                {"objective": obj})
    for st in evaluator._alerts.values():
        out.add(s + "alert_active", "gauge",
                1.0 if st["active"] else 0.0,
                {"objective": st["objective"],
                 "severity": st["severity"]})
    return out.lines


def prometheus_text(engines=None, run_dir=None, registry=None,
                    now=None, router=None, sources=None, slo=None):
    """The full exposition: registry + SLO gauges (+ router gauges,
    the live SLO engine's burn/budget gauges, and scraped-and-merged
    remote ``sources``, for a fleet front-end), newline-terminated
    Prometheus text format."""
    lines = registry_lines(registry) + slo_lines(engines, run_dir,
                                                 now=now)
    if router is not None:
        lines += router_lines(router)
    if router is not None or engines:
        # the per-tenant chargeback gauges: router-level shares/weights
        # when fronting a fleet, per-replica device/page integrals when
        # exporting engines (each worker's own exporter emits these, so
        # the router's scrape-and-merge carries them fleet-wide)
        lines += tenant_lines(router=router, engines=engines)
    if slo is not None:
        lines += slo_engine_lines(slo)
    if sources:
        texts = ["\n".join(lines) + "\n"]
        for target in sources:
            try:
                texts.append(scrape(target))
            except Exception:
                continue  # a restarting replica misses one scrape tick
        return merge_expositions(texts)
    return "\n".join(lines) + "\n"


def scrape(target, timeout=5.0):
    """Fetch one exposition: an ``http(s)://`` URL (a per-replica
    :class:`MetricsExporter`) or a textfile path — the two transports a
    multi-process serve fleet exports over."""
    t = str(target)
    if t.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(t, timeout=timeout) as resp:
            return resp.read().decode("utf-8")
    with open(t, encoding="utf-8") as f:
        return f.read()


def merge_expositions(texts):
    """Fuse N Prometheus expositions into one: ``# TYPE`` declared once
    per family (first seen wins), and samples with IDENTICAL keys
    (name + labels) SUMMED — correct for counters and histogram
    ``_bucket``/``_sum``/``_count`` series, and for additive gauges
    (queue depths, running counts); non-additive gauges must carry a
    distinguishing label, which the per-replica SLO gauges
    (``replica="N"``) and router gauges do. This is the router-side
    merge that extends the PR-13 signal plane to OUT-of-process
    replicas (``live_engines()`` only ever saw this process's)."""
    types = {}        # family -> type
    order = []        # sample keys, first-seen order
    values = {}       # key -> summed float (or raw string passthrough)
    raw = {}          # key -> original value string (single source)
    counts = {}
    for text in texts:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4:
                    types.setdefault(parts[2], parts[3])
                continue
            if line.startswith("#"):
                continue
            key, _, val = line.rpartition(" ")
            if not key:
                continue
            if key not in values:
                order.append(key)
                values[key] = 0.0
                counts[key] = 0
            try:
                values[key] += float(val)
            except ValueError:
                pass
            raw[key] = val
            counts[key] += 1
    out = _Lines()
    for key in order:
        family = key.split("{", 1)[0]
        if family not in types:
            # histogram samples carry suffixes; their TYPE is declared
            # on the base family (an exact-name match — e.g. the SLO
            # ``*_count`` gauges — always wins over the strip)
            for suffix in ("_bucket", "_sum", "_count"):
                if family.endswith(suffix) and \
                        family[:-len(suffix)] in types:
                    family = family[:-len(suffix)]
                    break
        if family in types and family not in out._declared:
            out._declared.add(family)
            out.raw(f"# TYPE {family} {types[family]}")
        if counts[key] == 1:
            # single source: pass the value through VERBATIM so the
            # merge is bitwise-lossless (the common per-replica case)
            out.raw(f"{key} {raw[key]}")
        else:
            out.raw(f"{key} {_fmt(values[key])}")
    return "\n".join(out.lines) + "\n"


def statusz_data(router=None, slo=None, engines=None, now=None):
    """The live fleet pane as plain data (the ``/statusz?format=json``
    body): fleet topology (replica id / state / incarnation from the
    pool), per-replica SLO table (the evaluator's cached last scrape,
    falling back to local engine stats — NO new HTTP calls on render),
    burn/budget/active alerts, and the router's recent scale/requeue
    events. Pull-only: rendered per GET, nothing on the serve path."""
    data = {"now": now, "fleet": [], "router": None, "slo": None,
            "events": [], "replica_slo": {}, "tenants": {},
            "fairness": None}
    pool = getattr(router, "pool", None)
    if pool is not None:
        data["fleet"] = pool.topology()
    if router is not None:
        st = router.stats()
        data["router"] = {k: st.get(k) for k in
                          ("queue_depth", "inflight", "dispatched",
                           "requeued", "rejected", "completed",
                           "replicas", "scale_ups", "scale_downs")}
        for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
            if st.get(key):
                data["router"][key] = st[key]
        data["events"] = [dict(e) for e in
                          getattr(router, "recent_events", ())]
        # the tenant chargeback/fairness pane (obs.usage, pull-only)
        from . import usage as _usage

        tu = _usage.router_tenant_usage(router)
        data["tenants"] = tu["tenants"]
        data["fairness"] = _usage.fairness_audit(tu["tenants"])
    if slo is not None:
        s = slo.status()
        data["slo"] = s
        data["replica_slo"] = s.get("replica_slo") or {}
    if not data["replica_slo"] and engines:
        for i, eng in enumerate(engines):
            try:
                st = eng.stats()
            except Exception:
                continue
            rep = str(getattr(eng, "replica_id", i))
            row = {}
            for key in ("ttft_ms", "tpot_ms"):
                d = st.get(key) or {}
                for q in ("p50", "p99"):
                    if d.get(q) is not None:
                        row[f"{key[:-3]}_{q}_ms"] = d[q]
            if row:
                data["replica_slo"][rep] = row
    return data


def _esc(v):
    return (str(v).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def _td(v):
    if isinstance(v, float):
        return f"{v:.4g}"
    return "-" if v is None else _esc(v)


def _html_table(headers, rows):
    h = "".join(f"<th>{_esc(c)}</th>" for c in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_td(c)}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{h}</tr>{body}</table>"


def render_statusz_html(data):
    """``/statusz`` as a dependency-free single HTML page: fleet
    topology, per-replica SLO table, per-objective burn/budget, active
    alerts, recent router events."""
    parts = ["<!DOCTYPE html><html><head><title>statusz</title>",
             "<style>body{font-family:monospace;margin:1em}",
             "table{border-collapse:collapse;margin:0.5em 0}",
             "td,th{border:1px solid #999;padding:2px 8px;",
             "text-align:right}th{background:#eee}",
             ".firing{color:#b00;font-weight:bold}</style>",
             "</head><body><h1>paddle_tpu fleet statusz</h1>"]
    slo = data.get("slo") or {}
    active = slo.get("active_alerts") or []
    if active:
        parts.append('<p class="firing">FIRING: ' + ", ".join(
            f'{_esc(a["objective"])} [{_esc(a["severity"])}]'
            for a in active) + "</p>")
    else:
        parts.append("<p>no active SLO alerts</p>")
    if data.get("fleet"):
        parts.append("<h2>fleet topology</h2>")
        parts.append(_html_table(
            ["replica", "state", "incarnation", "outstanding_tokens",
             "inflight"],
            [[r.get("replica"), r.get("state"), r.get("incarnation"),
              r.get("outstanding_tokens"), r.get("inflight")]
             for r in data["fleet"]]))
    if slo.get("objectives"):
        parts.append("<h2>SLO burn &amp; budget</h2>")
        windows = sorted(
            {w for o in slo["objectives"] for w in (o.get("burn")
                                                    or {})})
        parts.append(_html_table(
            ["objective", "target"] + [f"burn {w}" for w in windows]
            + ["budget remaining"],
            [[o.get("name"), o.get("target")]
             + [(o.get("burn") or {}).get(w) for w in windows]
             + [o.get("budget_remaining")]
             for o in slo["objectives"]]))
    if data.get("replica_slo"):
        keys = sorted({k for v in data["replica_slo"].values()
                       for k in v})
        parts.append("<h2>per-replica SLO</h2>")
        parts.append(_html_table(
            ["replica"] + keys,
            [[rep] + [vals.get(k) for k in keys]
             for rep, vals in sorted(data["replica_slo"].items())]))
    if data.get("tenants"):
        fair = data.get("fairness") or {}
        flag = "" if fair.get("ok", True) else \
            f' <span class="firing">DRIFT {fair.get("max_drift"):.3f}' \
            f' &gt; {fair.get("threshold"):.3f}' \
            f' ({_esc(fair.get("worst_tenant"))})</span>'
        parts.append(f"<h2>tenants</h2>{flag}" if flag
                     else "<h2>tenants</h2>")
        parts.append(_html_table(
            ["tenant", "weight", "weight_share", "share",
             "served_tokens", "queued", "completed", "rejected",
             "rate_holds", "requeued", "preemptions", "ttft_p99_ms",
             "e2e_p99_ms"],
            [[tname, d.get("weight"), d.get("weight_share"),
              d.get("share"), d.get("served_tokens"), d.get("queued"),
              d.get("completed"), d.get("rejected"),
              d.get("rate_holds"), d.get("requeued"),
              d.get("preemptions"), d.get("ttft_ms_p99"),
              d.get("e2e_ms_p99")]
             for tname, d in sorted(data["tenants"].items())]))
    if data.get("router"):
        r = data["router"]
        parts.append("<h2>router</h2>")
        parts.append(_html_table(
            sorted(k for k in r if not isinstance(r[k], dict)),
            [[r[k] for k in sorted(r) if not isinstance(r[k], dict)]]))
    if data.get("events"):
        parts.append("<h2>recent router events</h2>")
        parts.append(_html_table(
            ["t", "kind", "detail"],
            [[e.get("t"), e.get("kind"),
              "; ".join(f"{k}={v}" for k, v in sorted(e.items())
                        if k not in ("t", "kind"))]
             for e in data["events"]]))
    log = slo.get("alert_log") or []
    if log:
        parts.append("<h2>alert history</h2>")
        parts.append(_html_table(
            ["at", "kind", "objective", "severity", "burn_short",
             "burn_long", "worst_replica"],
            [[e.get("at"), e.get("kind"), e.get("objective"),
              e.get("severity"), e.get("burn_short"),
              e.get("burn_long"), e.get("worst_replica")]
             for e in log]))
    parts.append("</body></html>")
    return "".join(parts)


def write_textfile(path, engines=None, run_dir=None, registry=None,
                   router=None, sources=None, slo=None):
    """Atomic textfile export (node_exporter textfile-collector
    convention): write to a tmp sibling, fsync-free rename — a scraper
    reading mid-write sees the previous complete snapshot, never a torn
    one. Returns ``path``."""
    body = prometheus_text(engines=engines, run_dir=run_dir,
                           registry=registry, router=router,
                           sources=sources, slo=slo)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(body)
    os.replace(tmp, path)
    return path


def parse_prometheus_text(text):
    """``{metric-with-labels: float}`` from exposition text — the test
    and bench-side inverse of :func:`prometheus_text` (floats parse
    back exactly: values are emitted in ``repr`` round-trip form)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            out[key] = float(val)
        except ValueError:
            pass
    return out


class MetricsExporter:
    """Serve :func:`prometheus_text` on ``GET /metrics`` over localhost
    HTTP (``port=0`` → ephemeral, read ``.port``/``.url`` after
    :meth:`start`). The handler renders on each scrape — pull-based, so
    an idle exporter costs nothing between scrapes. Also usable as a
    context manager, and as a handle for periodic
    :meth:`write_textfile` snapshots."""

    def __init__(self, engines=None, run_dir=None, host="127.0.0.1",
                 port=0, registry=None, router=None, sources=None,
                 slo=None):
        self.engines = None if engines is None else list(engines)
        self.run_dir = run_dir
        self.host = str(host)
        self.port = int(port)
        self.registry = registry
        # fleet front-end mode: a serving.fleet.Router's gauges, plus
        # remote per-replica exporters scraped-and-merged per render,
        # plus the live SLO engine's burn/budget gauges + /statusz
        self.router = router
        self.sources = None if sources is None else list(sources)
        self.slo = slo
        self._httpd = None
        self._thread = None

    def register_engine(self, engine):
        """Pin an explicit engine set (otherwise every live engine in
        the process is exported)."""
        if self.engines is None:
            self.engines = []
        self.engines.append(engine)

    def render(self):
        return prometheus_text(engines=self.engines,
                               run_dir=self.run_dir,
                               registry=self.registry,
                               router=self.router,
                               sources=self.sources,
                               slo=self.slo)

    def render_statusz(self, fmt="html"):
        """The /statusz body: live fleet topology + SLO pane (the
        pane ``tools/fleet_report.py`` only reconstructs post-mortem).
        ``fmt="json"`` returns the machine-readable form."""
        import json as _json

        data = statusz_data(router=self.router, slo=self.slo,
                            engines=self.engines)
        if fmt == "json":
            return _json.dumps(data, default=str, indent=1)
        return render_statusz_html(data)

    def write_textfile(self, path):
        return write_textfile(path, engines=self.engines,
                              run_dir=self.run_dir,
                              registry=self.registry,
                              router=self.router,
                              sources=self.sources,
                              slo=self.slo)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}/metrics"

    def start(self):
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                path, _, query = self.path.partition("?")
                if path == "/statusz":
                    fmt = "json" if "format=json" in query else "html"
                    ctype = ("application/json; charset=utf-8"
                             if fmt == "json"
                             else "text/html; charset=utf-8")
                    try:
                        body = exporter.render_statusz(fmt) \
                            .encode("utf-8")
                    except Exception as e:
                        self.send_error(500,
                                        f"{type(e).__name__}: {e}")
                        return
                elif path in ("/metrics", "/"):
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    try:
                        body = exporter.render().encode("utf-8")
                    except Exception as e:  # surface, don't kill
                        self.send_error(500,
                                        f"{type(e).__name__}: {e}")
                        return
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not stdout news
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pt-metrics-exporter", daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
