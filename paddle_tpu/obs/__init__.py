"""paddle_tpu.obs — unified telemetry: metrics registry + span tracer.

The reference ships a first-class profiler (``fluid/profiler.py`` over
the C++ platform profiler); this package is its TPU-native counterpart
plus the production metrics layer the reference keeps in VLOG counters:

- ``metrics``  — process-wide registry of named Counters / Gauges /
  fixed-bucket Histograms; ``snapshot()`` / ``reset()``; thread-safe,
  allocation-free on the tick path.
- ``trace``    — ``span(name, **attrs)`` wall-time spans in a bounded
  ring buffer, exported as Chrome ``chrome://tracing`` JSON; opt-in via
  env ``PADDLE_TPU_TRACE=1`` or ``enable_tracing()``.
- ``report``   — human-readable table / JSON dump of the registry
  (``tools/obs_report.py`` is the CLI front door).
- ``journal``  — per-run JSONL flight recorder (``RunJournal``): run
  header, per-step records, discrete events, anomaly firings, and an
  MFU/goodput summary; env ``PADDLE_TPU_RUN_DIR`` auto-starts one
  (``tools/run_report.py`` renders and diffs runs).
- ``anomaly``  — stateful detectors (loss spike/plateau, nonfinite
  streak, throughput drop, dataloader starvation) evaluated on each
  journal step; thresholds via env ``PADDLE_TPU_ANOMALY``.
- ``mfu``      — MFU/goodput accounting from XLA ``cost_analysis``
  FLOPs per compiled executable + the configured peak
  (``PADDLE_TPU_PEAK_FLOPS`` / ``mfu.set_peak_flops``).
- ``spmd``     — SPMD observability: CollectiveProfile (per-kind
  collective counts/bytes parsed from the executable's HLO, attributed
  to mesh axes), comm roofline vs ``PADDLE_TPU_ICI_BW``/chip table,
  ShardingReport per Executor cache entry, per-device memory gauges +
  Chrome-trace device lanes (``tools/shard_report.py`` is the CLI).
- ``reqtrace`` — request-scoped distributed tracing: assemble the
  ``req.*`` journal events (router + replicas) into per-request
  timelines, exact tail-latency phase attribution (rate-limit wait /
  router queue / scheduler queue / prefill / preemption loss summing
  to e2e), and Perfetto request lanes with flow arrows across
  requeues (``tools/request_report.py`` is the CLI).
- ``fleet``    — cross-rank aggregation over per-rank journals
  (``<run_dir>/rank_NN/``, written when gang launchers hand workers
  ``PADDLE_TPU_RANK``): step alignment, cross-rank skew,
  straggler/hang attribution, merged request percentiles, merged
  Chrome traces with pid=rank lanes (``tools/fleet_report.py`` is the
  CLI).
- ``lockdep``  — opt-in runtime lock-order validation (env
  ``PADDLE_TPU_LOCKDEP``): instrumented ``lock(name)``/``rlock(name)``
  factories feed a process-wide acquisition-order graph; the first
  cycle raises/journals a PTC004 with both witness stacks, and
  ``lockdep.held_ms.<name>`` histograms land in the registry. The
  runtime half of ``analysis.concurrency``'s static lint.
- ``export``   — live SLO signal plane: the registry + per-replica
  serving SLOs + per-rank heartbeat ages as Prometheus text over a
  localhost HTTP endpoint (``MetricsExporter``, which also serves the
  ``/statusz`` fleet status page) or an atomic textfile.
- ``timeseries`` — fixed-interval rolling windows over registry
  snapshots or scraped expositions (``SeriesStore``): windowed counter
  rates, gauge trends, and histogram percentiles / threshold
  fractions over the last 1m/5m/30m/3h, exact under a ManualClock.
- ``slo``      — declarative serving SLOs on top of ``timeseries``:
  per-objective error budgets, Google-SRE multi-window multi-burn-rate
  alerting (fast page 14.4x over 5m+30m, slow warn 6x over 30m+3h),
  latched ``slo.fire``/``slo.clear`` journal events with worst-replica
  attribution, and post-hoc ``evaluate_run`` for finished run dirs
  (``tools/slo_report.py`` is the CLI; ``serve_bench --slo`` the
  exit gate).

Instrumented sites (all zero-overhead when idle — one flag/None check,
no host sync, mirroring the ``resilience.inject`` ``if ACTIVE`` hooks):

======================  ====================================================
subsystem               instruments
======================  ====================================================
static_/executor.py     ``executor.jit_cache.hits|misses``,
                        ``executor.compile_ms``, ``executor.run_ms``,
                        ``executor.fetch_ms``; spans ``executor.compile``,
                        ``executor.run``
analysis (passes)       ``analysis.pass.<name>.ms`` per optimization pass
core/dispatch.py        ``dispatch.ops_total``, ``dispatch.op.<type>``
                        behind ``enable_op_sampling()`` /
                        env ``PADDLE_TPU_OBS_SAMPLE`` (off by default:
                        the eager hot path pays one None check)
io_/dataloader.py       ``dataloader.queue_depth`` gauge,
                        ``dataloader.producer_wait_ms``,
                        ``dataloader.consumer_wait_ms``,
                        ``dataloader.worker_restarts``; span
                        ``dataloader.next``
resilience              ``resilience.retries|steps|nonfinite|skipped|``
                        ``rollbacks|degraded``
framework/io.py         ``checkpoint.save_ms|load_ms|verify_ms``,
                        ``checkpoint.saves|loads|fallbacks``; spans
                        ``checkpoint.save|load``
utils/profiler.py       ``step_timer.step_ms`` (StepTimer rebase)
======================  ====================================================
"""
from __future__ import annotations

import os as _os

from . import lockdep  # noqa: F401  (first: others build locks through it)
from . import metrics, trace, report, anomaly, mfu, journal, spmd  # noqa: F401,E501
from . import fleet, export, reqtrace  # noqa: F401
from . import timeseries, slo  # noqa: F401  (after metrics/export)
from .metrics import (counter, gauge, histogram, snapshot, reset,  # noqa: F401
                      Counter, Gauge, Histogram, Registry, REGISTRY)
from .trace import (span, enable_tracing, disable_tracing,  # noqa: F401
                    tracing_enabled, clear_trace, trace_events,
                    export_chrome_trace)
from .journal import RunJournal, start_run, end_run  # noqa: F401
from .export import MetricsExporter  # noqa: F401

__all__ = [
    "metrics", "trace", "report", "anomaly", "mfu", "journal", "spmd",
    "fleet", "export", "reqtrace", "lockdep", "timeseries", "slo",
    "counter", "gauge", "histogram", "snapshot", "reset",
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "span", "enable_tracing", "disable_tracing", "tracing_enabled",
    "clear_trace", "trace_events", "export_chrome_trace",
    "enable_op_sampling", "disable_op_sampling", "op_sampling_enabled",
    "RunJournal", "start_run", "end_run", "MetricsExporter",
]

# -- eager op sampling -------------------------------------------------------
# The dispatcher cannot afford a registry lookup per op, so sampling is
# push-style: enabling installs a closure over pre-interned counters into
# core.dispatch (the exact pattern resilience.inject uses for nan_op).

_op_sampling = False


def enable_op_sampling(every=1):
    """Count eager op dispatches into ``dispatch.ops_total`` and
    ``dispatch.op.<type>``, sampling one in ``every`` calls. Off by
    default; also enabled at import by env ``PADDLE_TPU_OBS_SAMPLE``
    (its integer value is the sampling stride, ``1`` = every op)."""
    global _op_sampling
    from ..core import dispatch

    every = max(1, int(every))
    total = metrics.counter("dispatch.ops_total")
    per_op: dict = {}  # op type -> Counter, interned outside the lock
    if every == 1:
        def hook(name):
            total.inc()
            c = per_op.get(name)
            if c is None:
                c = per_op[name] = metrics.counter("dispatch.op." + name)
            c.inc()
    else:
        state = {"n": 0}

        def hook(name):
            # stride sampling: the +every correction keeps ops_total an
            # unbiased estimate of the true dispatch count
            state["n"] += 1
            if state["n"] % every:
                return
            total.inc(every)
            c = per_op.get(name)
            if c is None:
                c = per_op[name] = metrics.counter("dispatch.op." + name)
            c.inc(every)
    dispatch.set_op_metrics_hook(hook)
    _op_sampling = True


def disable_op_sampling():
    global _op_sampling
    from ..core import dispatch

    dispatch.set_op_metrics_hook(None)
    _op_sampling = False


def op_sampling_enabled():
    return _op_sampling


_sample_env = _os.environ.get("PADDLE_TPU_OBS_SAMPLE", "")
if _sample_env.lower() not in ("", "0", "false"):
    try:
        enable_op_sampling(int(_sample_env))
    except ValueError:
        enable_op_sampling(1)
