"""Stateful anomaly detectors over the run journal's step stream.

Each detector sees every step record (a plain dict, see
``obs.journal.RunJournal.record_step``) and decides whether this run is
going sideways — the automatic "page someone" signal the MLPerf-era TPU
operations playbooks keep in scattered log-scraping. Firing is cheap
and host-side only: rolling windows of floats, no device work.

A fired anomaly becomes three things (wired by ``AnomalyEngine``):

- an ``obs`` counter tick under ``anomaly.<name>``,
- a journal ``anomaly`` record, and
- an optional user callback (e.g. to flip a ``resilience.RecoveryPolicy``
  to a more conservative mode, or to trigger an early checkpoint).

Detectors re-arm per streak: a 50-step plateau fires once, not 50 times.

Thresholds are constructor kwargs; env ``PADDLE_TPU_ANOMALY`` overrides
them process-wide with the chaos-spec grammar
(``"loss_spike:factor=10;throughput_drop:factor=3"``, or ``"off"`` to
disable every detector the journal would otherwise install).
"""
from __future__ import annotations

import math
import os
from collections import deque

from . import metrics as _metrics

__all__ = [
    "Detector", "LossSpike", "LossPlateau", "NonfiniteStreak",
    "ThroughputDrop", "DataloaderStarvation", "TtftSpike",
    "TenantHog", "AnomalyEngine", "default_detectors",
    "serving_detectors", "DETECTORS", "SERVING_DETECTORS",
]


def _finite(v):
    return v is not None and isinstance(v, (int, float)) \
        and math.isfinite(v)


def _median(values):
    s = sorted(values)
    n = len(s)
    if not n:
        return None
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class Detector:
    """One stateful check; ``update(record)`` returns a detail dict when
    the anomaly fires (``None`` otherwise)."""

    name = "detector"

    def update(self, rec):  # pragma: no cover - overridden
        return None


class LossSpike(Detector):
    """Loss jumps far above its rolling median: fired when
    ``loss > median + factor * max(MAD, floor)`` over the last
    ``window`` finite losses (MAD = median absolute deviation, so a
    noisy-but-stable loss doesn't false-positive)."""

    name = "loss_spike"

    def __init__(self, window=32, factor=8.0, min_steps=5):
        self.window = int(window)
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        self._losses = deque(maxlen=self.window)
        self._armed = True

    def update(self, rec):
        loss = rec.get("loss")
        if not _finite(loss):
            return None
        fired = None
        if len(self._losses) >= self.min_steps:
            med = _median(self._losses)
            mad = _median([abs(v - med) for v in self._losses])
            floor = 1e-3 * max(1.0, abs(med))
            threshold = med + self.factor * max(mad, floor)
            if loss > threshold:
                if self._armed:  # once per excursion, not per step
                    self._armed = False
                    fired = {"loss": loss, "median": med,
                             "threshold": threshold}
            else:
                self._armed = True
        self._losses.append(loss)
        return fired


class LossPlateau(Detector):
    """No meaningful improvement for a full window: the best loss in
    the last ``window`` steps failed to improve on the best before the
    window by ``rel_eps`` (relative). Fires once per plateau."""

    name = "loss_plateau"

    def __init__(self, window=50, rel_eps=1e-3):
        self.window = int(window)
        self.rel_eps = float(rel_eps)
        self._recent = deque(maxlen=self.window)
        self._best_before = None
        self._armed = True

    def update(self, rec):
        loss = rec.get("loss")
        if not _finite(loss):
            return None
        if len(self._recent) == self.window:
            leaving = self._recent[0]
            self._best_before = leaving if self._best_before is None \
                else min(self._best_before, leaving)
        self._recent.append(loss)
        if self._best_before is None or len(self._recent) < self.window:
            return None
        best_recent = min(self._recent)
        margin = self.rel_eps * max(abs(self._best_before), 1e-12)
        if best_recent > self._best_before - margin:
            if self._armed:
                self._armed = False
                return {"best_before": self._best_before,
                        "best_recent": best_recent,
                        "window": self.window}
            return None
        self._armed = True
        return None


class NonfiniteStreak(Detector):
    """``threshold`` consecutive steps that were nonfinite (skipped /
    rolled back / NaN loss). Fires once per streak — the signal that a
    skip policy has stopped recovering and is just discarding work."""

    name = "nonfinite_streak"

    def __init__(self, threshold=3):
        self.threshold = int(threshold)
        self._streak = 0

    def update(self, rec):
        loss = rec.get("loss")
        bad = rec.get("nonfinite") or rec.get("skipped") or \
            (loss is not None and isinstance(loss, float)
             and not math.isfinite(loss))
        if not bad:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak == self.threshold:
            return {"streak": self._streak}
        return None


class ThroughputDrop(Detector):
    """Step time degrades to ``factor`` x its rolling median (same
    windowing as LossSpike, on ``step_ms``)."""

    name = "throughput_drop"

    def __init__(self, window=32, factor=2.5, min_steps=8):
        self.window = int(window)
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        self._times = deque(maxlen=self.window)
        self._armed = True

    def update(self, rec):
        ms = rec.get("step_ms")
        if not _finite(ms) or ms <= 0:
            return None
        fired = None
        if len(self._times) >= self.min_steps:
            med = _median(self._times)
            if med and ms > self.factor * med:
                if self._armed:  # once per slowdown, not per slow step
                    self._armed = False
                    fired = {"step_ms": ms, "median_ms": med}
            else:
                self._armed = True
        self._times.append(ms)
        return fired


class DataloaderStarvation(Detector):
    """The train loop spent more than ``ratio`` of a step waiting on
    input (per-step consumer-wait delta vs step time, both host-side
    numbers the journal already carries) — the input pipeline, not the
    device, is the bottleneck."""

    name = "dataloader_starvation"

    def __init__(self, ratio=0.5, min_wait_ms=1.0, min_steps=3):
        self.ratio = float(ratio)
        self.min_wait_ms = float(min_wait_ms)
        self.min_steps = int(min_steps)
        self._seen = 0
        self._armed = True

    def update(self, rec):
        ms, wait = rec.get("step_ms"), rec.get("dl_wait_ms")
        if not _finite(ms) or not _finite(wait) or ms <= 0:
            return None
        self._seen += 1
        if self._seen < self.min_steps:
            return None
        if wait >= self.min_wait_ms and wait / ms > self.ratio:
            if self._armed:  # once per starvation episode
                self._armed = False
                return {"dl_wait_ms": wait, "step_ms": ms,
                        "ratio": wait / ms}
            return None
        self._armed = True
        return None


class TtftSpike(Detector):
    """The serve path's LossSpike: windowed TTFT p99 (ms, from
    ``obs.timeseries`` via the SLO evaluator's tick record) jumps
    above ``median + factor * max(MAD, floor)`` over the last
    ``window`` observations. Same once-per-excursion re-arm as the
    training detectors — a sustained latency excursion fires once,
    recovery re-arms it."""

    name = "ttft_spike"

    def __init__(self, window=32, factor=6.0, min_steps=5,
                 floor_ms=0.5):
        self.window = int(window)
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        self.floor_ms = float(floor_ms)
        self._values = deque(maxlen=self.window)
        self._armed = True

    def update(self, rec):
        v = rec.get("ttft_ms")
        if not _finite(v) or v < 0:
            return None
        fired = None
        if len(self._values) >= self.min_steps:
            med = _median(self._values)
            mad = _median([abs(x - med) for x in self._values])
            threshold = med + self.factor * max(mad, self.floor_ms)
            if v > threshold:
                if self._armed:  # once per excursion, not per tick
                    self._armed = False
                    fired = {"ttft_ms": v, "median_ms": med,
                             "threshold_ms": threshold}
            else:
                self._armed = True
        self._values.append(v)
        return fired


class TenantHog(Detector):
    """One tenant's measured served-token share runs ``margin`` above
    its configured weight share for ``patience`` consecutive
    observations — a tenant is eating more of the fleet than its
    weight entitles it to, persistently (transient overshoot right
    after a burst is normal for a work-conserving scheduler; the
    streak filters it). Reads the ``tenant_shares`` field
    (``{tenant: {share, weight_share}}``) the router folds into its
    throttled SLO tick record via ``obs.usage.fairness_record``.
    Same once-per-episode discipline as StragglerDetector: fires when
    the SAME tenant holds the worst overshoot for ``patience``
    straight ticks; the overshoot dropping under ``margin`` (or the
    hog changing) resets the streak and re-arms."""

    name = "tenant_hog"

    def __init__(self, margin=0.2, patience=3, min_served=32):
        self.margin = float(margin)
        self.patience = max(1, int(patience))
        self.min_served = int(min_served)
        self._tenant = None
        self._streak = 0

    def update(self, rec):
        shares = rec.get("tenant_shares")
        if not isinstance(shares, dict) or len(shares) < 2:
            return None
        served = rec.get("tenant_served_total")
        if _finite(served) and served < self.min_served:
            return None  # too few tokens for a share to mean anything
        worst, over = None, 0.0
        for tenant, d in shares.items():
            share, wshare = d.get("share"), d.get("weight_share")
            if not _finite(share) or not _finite(wshare):
                continue
            o = share - wshare
            if worst is None or o > over:
                worst, over = tenant, o
        if worst is None or over < self.margin:
            self._tenant, self._streak = None, 0
            return None
        if worst != self._tenant:
            self._tenant = worst
            self._streak = 0
        self._streak += 1
        if self._streak == self.patience:  # once per episode
            d = shares[worst]
            return {"tenant": worst, "share": d.get("share"),
                    "weight_share": d.get("weight_share"),
                    "over": over, "streak": self._streak}
        return None


DETECTORS = {cls.name: cls for cls in
             (LossSpike, LossPlateau, NonfiniteStreak, ThroughputDrop,
              DataloaderStarvation, TtftSpike, TenantHog)}

# the serve-path subset: ttft_spike reads the windowed TTFT p99,
# throughput_drop reads the per-token latency implied by the windowed
# token rate (both fed by obs.slo.SLOEvaluator's tick record) — the
# AnomalyEngine blind spot ISSUE 19 closes: detectors used to see only
# training step records. tenant_hog reads the per-tenant share fields
# the router folds into the same tick (obs.usage.fairness_record).
SERVING_DETECTORS = ("ttft_spike", "throughput_drop", "tenant_hog")


def serving_detectors(env=None):
    """The serving detector set (``ttft_spike`` + ``throughput_drop``)
    with thresholds overridden by the same ``PADDLE_TPU_ANOMALY`` spec
    grammar ``default_detectors`` honors (non-serving names in the
    spec are ignored here, not errors — one env var configures both
    engines); ``"off"`` returns no detectors."""
    from ..utils.envspec import parse_spec

    spec = env if env is not None \
        else os.environ.get("PADDLE_TPU_ANOMALY", "")
    if spec.strip().lower() in ("off", "0", "false", "none"):
        return []
    overrides = {}
    for name, cfg in parse_spec(spec):
        if name in SERVING_DETECTORS:
            overrides[name] = cfg
    return [DETECTORS[name](**overrides.get(name, {}))
            for name in SERVING_DETECTORS]


def default_detectors(env=None):
    """One instance of every detector, with thresholds overridden by
    the ``PADDLE_TPU_ANOMALY`` spec (the shared
    ``utils.envspec`` grammar ``"name:key=val,key=val;name2"``, same as
    ``PADDLE_TPU_CHAOS``; ``"off"`` returns no detectors)."""
    from ..utils.envspec import parse_spec

    spec = env if env is not None \
        else os.environ.get("PADDLE_TPU_ANOMALY", "")
    if spec.strip().lower() in ("off", "0", "false", "none"):
        return []
    overrides = {}
    for name, cfg in parse_spec(spec):
        if name not in DETECTORS:
            raise KeyError(
                f"PADDLE_TPU_ANOMALY names unknown detector '{name}' "
                f"(registered: {sorted(DETECTORS)})")
        overrides[name] = cfg
    return [cls(**overrides.get(name, {}))
            for name, cls in DETECTORS.items()]


class AnomalyEngine:
    """Fans one step record out to every detector; a firing ticks
    ``anomaly.<name>`` in the metrics registry, is returned to the
    caller (the journal records it), and reaches each registered
    callback — exceptions in callbacks are swallowed so a buggy
    reaction can't kill the train loop."""

    def __init__(self, detectors=None, callback=None):
        self.detectors = list(detectors) if detectors is not None \
            else default_detectors()
        self.callbacks = [callback] if callback is not None else []
        self.fired = []  # (name, step, detail) history, bounded
        self._fired_cap = 256

    def add_callback(self, fn):
        self.callbacks.append(fn)

    def observe(self, rec):
        out = []
        for det in self.detectors:
            try:
                detail = det.update(rec)
            except Exception:
                continue  # a broken detector must not break the step
            if detail is None:
                continue
            _metrics.counter("anomaly." + det.name).inc()
            fired = {"name": det.name, "step": rec.get("step"),
                     "detail": detail}
            out.append(fired)
            if len(self.fired) < self._fired_cap:
                self.fired.append(fired)
            for cb in self.callbacks:
                try:
                    cb(fired)
                except Exception:
                    pass
        return out
