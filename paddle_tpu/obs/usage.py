"""Per-tenant usage metering, cost attribution, and fairness auditing.

The chargeback plane for the serve fleet (ROADMAP item 5's quota /
capacity prerequisite): production TPU serving is priced in
device-seconds and KV-page occupancy — the capacity currency of Ragged
Paged Attention (arXiv 2604.15464) and the cost-per-request framing of
the Gemma-on-TPU serving comparison (arXiv 2605.25645) — so every
request must answer "which tenant, how many device-nanoseconds, how
many page-nanoseconds?".

Attribution model (everything in **integer nanoseconds** — integer
addition is exact and associative, so per-tenant sums telescope to the
replica totals *bitwise*, which float accumulation cannot promise):

- **Device-seconds** (:class:`UsageMeter`, attached to every
  ``ServeEngine`` as ``engine.usage``): each prefill's wall span is
  charged to its request; each decode step's span is split across the
  batch's live lanes by ``divmod`` (the first ``remainder`` lanes get
  one extra nanosecond), so ``sum(tenant device_ns) == sum(request
  device_ns) == busy_ns`` is an identity, not an approximation. A
  decode pass that ends with zero live lanes (every lane preempted)
  charged nobody and is *not* busy time — busy is defined as
  attributed compute.
- **KV page-seconds** (``PagedKVCache`` stamps, same clock as the
  scheduler): the cache integrates pages-held x time per sequence
  between alloc/extend/free, closing the integral on free — so the
  integrals ACCUMULATE across preempt/re-admit incarnations and
  alloc==free closure is asserted by ``cache.verify()``.

Everything else here is a **pull-only reader** (the PR-4 zero-overhead
contract: the serve path never calls into this module; poisoned
readers must not perturb a routed lifecycle): per-engine and
per-router rollups, the fairness audit (measured served-token share vs
configured weight share), journal-record rollups for the post-hoc
``tools/usage_report.py`` chargeback table, and per-tenant SLO slices
via ``obs.slo.evaluate_run``.
"""
from __future__ import annotations

from .metrics import exact_percentile

__all__ = ["DEFAULT_TENANT", "DEFAULT_FAIRNESS_DRIFT_THRESHOLD",
           "TickingClock", "UsageMeter", "engine_tenant_usage",
           "router_tenant_usage", "fairness_audit", "fairness_record",
           "rollup_requests", "merge_tenant_rollups",
           "tenant_slo_slices"]

DEFAULT_TENANT = "default"

# fairness gate: |measured served-token share - configured weight
# share| above this absolute threshold is a drift violation (a
# weight-0.25 tenant measured at 0.5 — the self-tests' 2x violation —
# drifts by 0.25 and fires)
DEFAULT_FAIRNESS_DRIFT_THRESHOLD = 0.2


class TickingClock:
    """A ManualClock that also advances itself by a fixed ``tick`` on
    every read — so spans *inside* one engine step (which a plain
    ManualClock renders zero-width: nobody calls ``advance`` mid-step)
    are non-zero and fully deterministic. The default tick is a dyadic
    multiple of 1/512 s, which is integral in nanoseconds
    (``1e9 / 512 == 1953125``), so ManualClock fixtures stay exact to
    the nanosecond after the int conversion."""

    def __init__(self, start=0.0, tick=1.0 / 512):
        self.now = float(start)
        self.tick = float(tick)

    def __call__(self):
        t = self.now
        self.now = t + self.tick
        return t

    def advance(self, dt):
        self.now += float(dt)
        return self.now


def _ns(span_s):
    """Seconds -> integer nanoseconds (round-half-even like round())."""
    return int(round(float(span_s) * 1e9))


class UsageMeter:
    """Per-replica device-second attribution in integer nanoseconds.

    The engine charges it from ``step()`` (always-on plain-dict
    arithmetic, the same cost class as the ``serving.step_ms``
    histogram observe); everything else reads it pull-only.

    Invariants (``verify()``):

    - ``busy_ns == prefill_ns + decode_ns``
    - ``busy_ns == sum(device_ns.values())`` (per-tenant telescoping)
    - ``busy_ns == sum(request_ns.values())`` (per-request telescoping)
    """

    def __init__(self, replica_id=None):
        self.replica_id = replica_id
        self.busy_ns = 0
        self.prefill_ns = 0
        self.decode_ns = 0
        self.device_ns = {}      # tenant -> int ns
        self.request_ns = {}     # rid -> int ns
        self.tenant_of = {}      # rid -> resolved tenant
        self.prefills = 0        # prefill spans charged
        self.decode_steps = 0    # decode spans charged (>=1 live lane)

    def _charge(self, rid, tenant, ns):
        self.device_ns[tenant] = self.device_ns.get(tenant, 0) + ns
        self.request_ns[rid] = self.request_ns.get(rid, 0) + ns
        self.tenant_of[rid] = tenant

    def charge_prefill(self, req, span_s):
        """Charge one prefill's wall span wholly to its request."""
        ns = _ns(span_s)
        self.busy_ns += ns
        self.prefill_ns += ns
        self.prefills += 1
        self._charge(req.rid, req.tenant or DEFAULT_TENANT, ns)

    def charge_decode(self, reqs, span_s):
        """Split one decode step's wall span across its live lanes:
        ``divmod(ns, k)`` — the first ``remainder`` lanes (survivor
        order) carry one extra nanosecond, so the split is exact by
        construction. A zero-lane span charges nothing (and is not
        busy time — nothing computed)."""
        k = len(reqs)
        if not k:
            return
        ns = _ns(span_s)
        self.busy_ns += ns
        self.decode_ns += ns
        self.decode_steps += 1
        share, rem = divmod(ns, k)
        for i, req in enumerate(reqs):
            self._charge(req.rid, req.tenant or DEFAULT_TENANT,
                         share + (1 if i < rem else 0))

    def verify(self):
        """Assert the telescoping identities; returns True."""
        assert self.busy_ns == self.prefill_ns + self.decode_ns, \
            "busy != prefill + decode"
        assert self.busy_ns == sum(self.device_ns.values()), \
            "per-tenant device-ns do not telescope to busy"
        assert self.busy_ns == sum(self.request_ns.values()), \
            "per-request device-ns do not telescope to busy"
        return True

    def snapshot(self):
        """Plain-data copy (the ``stats()``-style view)."""
        return {
            "replica": self.replica_id,
            "busy_ns": self.busy_ns,
            "prefill_ns": self.prefill_ns,
            "decode_ns": self.decode_ns,
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "device_ns": dict(self.device_ns),
            "request_ns": dict(self.request_ns),
        }


# -- rollup plumbing ----------------------------------------------------------
_ZERO = {"requests": 0, "completed": 0, "cancelled": 0, "rejected": 0,
         "rate_holds": 0, "requeued": 0, "preempted_requests": 0,
         "preemptions": 0, "prompt_tokens": 0, "decode_tokens": 0,
         "device_ns": 0, "page_ns": 0}


def _slot(tenants, tenant):
    s = tenants.get(tenant)
    if s is None:
        s = dict(_ZERO)
        s["_lat"] = {"queue_ms": [], "ttft_ms": [], "tpot_ms": [],
                     "e2e_ms": []}
        tenants[tenant] = s
    return s


def _finalize(tenants):
    """Turn collected latency sample lists into exact percentiles
    (``exact_percentile`` — the same definition ``ServeEngine.stats()``
    and ``Router.stats()`` use) and drop the scratch lists."""
    for s in tenants.values():
        lat = s.pop("_lat", None) or {}
        for name, xs in lat.items():
            if xs:
                s[name + "_count"] = len(xs)
                s[name + "_p50"] = exact_percentile(xs, 50)
                s[name + "_p99"] = exact_percentile(xs, 99)
    return tenants


def _observe_latency(s, arrival_t=None, admit_t=None, first_token_t=None,
                     finish_t=None, n_generated=0):
    lat = s["_lat"]
    if arrival_t is not None and admit_t is not None:
        lat["queue_ms"].append((admit_t - arrival_t) * 1e3)
    if arrival_t is not None and first_token_t is not None:
        lat["ttft_ms"].append((first_token_t - arrival_t) * 1e3)
    if first_token_t is not None and finish_t is not None \
            and n_generated > 1:
        lat["tpot_ms"].append(
            (finish_t - first_token_t) * 1e3 / (n_generated - 1))
    if arrival_t is not None and finish_t is not None:
        lat["e2e_ms"].append((finish_t - arrival_t) * 1e3)


# -- live readers -------------------------------------------------------------
def engine_tenant_usage(engine):
    """Per-tenant rollup for ONE live engine (pull-only): outcomes,
    tokens and latency percentiles from ``engine.finished`` (the
    ``stats()`` discipline — exact, per-instance), device-ns from the
    meter, page-ns from the cache's closed integrals."""
    meter = engine.usage
    pu = engine.cache.page_usage()
    tenants = {}
    for r in engine.finished:
        s = _slot(tenants, r.tenant or DEFAULT_TENANT)
        s["requests"] += 1
        s["completed"] += 1
        if r.preemptions:
            s["preempted_requests"] += 1
            s["preemptions"] += r.preemptions
        s["prompt_tokens"] += len(r.prompt)
        s["decode_tokens"] += len(r.generated)
        _observe_latency(s, arrival_t=r.arrival_t, admit_t=r.admit_t,
                         first_token_t=r.first_token_t,
                         finish_t=r.finish_t,
                         n_generated=len(r.generated))
    for rid, ns in meter.request_ns.items():
        s = _slot(tenants, meter.tenant_of.get(rid, DEFAULT_TENANT))
        s["device_ns"] += ns
    for rid, ns in pu["closed_ns"].items():
        s = _slot(tenants, meter.tenant_of.get(rid, DEFAULT_TENANT))
        s["page_ns"] += ns
    return {
        "replica": engine.replica_id,
        "busy_ns": meter.busy_ns,
        "prefill_ns": meter.prefill_ns,
        "decode_ns": meter.decode_ns,
        "page_bytes": engine.cache.page_bytes,
        "page_open": len(pu["open"]),
        "seq_allocs": pu["seq_allocs"],
        "seq_frees": pu["seq_frees"],
        "tenants": _finalize(tenants),
    }


def router_tenant_usage(router):
    """Per-tenant router truth (pull-only): configured weight + weight
    share, measured served-token share, outcome counters, tokens, and
    latency percentiles over completed requests. The universe is every
    tenant that showed DEMAND (served, queued, completed, rejected, or
    rate-held); a configured-but-idle tenant carries no entitlement in
    this window (weight shares normalize over active tenants only —
    the weighted-deficit scheduler is work-conserving)."""
    served = dict(router._served)
    served_total = sum(served.values())
    tenants = {}
    for t in served:
        _slot(tenants, t)
    for t, q in router._queues.items():
        if q:
            _slot(tenants, t)["queued"] = len(q)
    for t, n in getattr(router, "_rejected_by_tenant", {}).items():
        _slot(tenants, t)["rejected"] = n
    for t, n in getattr(router, "_rate_holds_by_tenant", {}).items():
        _slot(tenants, t)["rate_holds"] = n
    for t, n in getattr(router, "_requeued_by_tenant", {}).items():
        _slot(tenants, t)["requeued"] = n
    for r in router.completed:
        s = _slot(tenants, r.tenant)
        s["requests"] += 1
        if r.state == "FINISHED":
            s["completed"] += 1
            s["prompt_tokens"] += len(r.prompt)
            s["decode_tokens"] += len(r.tokens)
            _observe_latency(s, arrival_t=r.arrival_t,
                             admit_t=r.admit_t,
                             first_token_t=r.first_token_t,
                             finish_t=r.finish_t,
                             n_generated=len(r.tokens))
        else:
            s["cancelled"] += 1
        if r.preemptions:
            s["preempted_requests"] += 1
            s["preemptions"] += r.preemptions
    weights = {t: router._policy(t).weight for t in tenants}
    wtotal = sum(weights.values())
    for t, s in tenants.items():
        s.setdefault("queued", 0)
        s["weight"] = weights[t]
        s["weight_share"] = (weights[t] / wtotal) if wtotal else 0.0
        s["served_tokens"] = served.get(t, 0.0)
        s["share"] = (served.get(t, 0.0) / served_total) \
            if served_total else 0.0
    return {"served_total": served_total,
            "tenants": _finalize(tenants)}


def fairness_audit(tenants, threshold=DEFAULT_FAIRNESS_DRIFT_THRESHOLD):
    """Measured served-token share vs configured weight share, per
    tenant: ``drift = |share - weight_share|``. ``tenants`` is any
    rollup shaped like ``router_tenant_usage(...)["tenants"]`` (each
    value carrying ``share`` and ``weight_share``). With fewer than
    two tenants there is nothing to be unfair between — ``max_drift``
    is 0.0 and the audit passes."""
    drifts = {}
    for t, s in tenants.items():
        share = float(s.get("share") or 0.0)
        wshare = float(s.get("weight_share") or 0.0)
        drifts[t] = {"share": share, "weight_share": wshare,
                     "drift": abs(share - wshare)}
    if len(drifts) < 2:
        worst, max_drift = None, 0.0
    else:
        worst = max(drifts, key=lambda t: drifts[t]["drift"])
        max_drift = drifts[worst]["drift"]
    return {"tenants": drifts, "max_drift": max_drift,
            "worst_tenant": worst, "threshold": float(threshold),
            "ok": max_drift <= float(threshold)}


def fairness_record(router):
    """The per-tick fairness fields the router folds into its
    throttled SLO tick's anomaly record (``tenant_hog``'s signal):
    measured share vs weight share per tenant plus total served
    tokens. None until at least two tenants have demand and tokens
    have been served — a one-tenant fleet has nothing to hog."""
    tu = router_tenant_usage(router)
    if not tu["served_total"] or len(tu["tenants"]) < 2:
        return None
    return {
        "tenant_served_total": tu["served_total"],
        "tenant_shares": {
            t: {"share": d["share"], "weight_share": d["weight_share"]}
            for t, d in tu["tenants"].items()},
    }


# -- post-hoc (journal) rollups ----------------------------------------------
def rollup_requests(records):
    """Per-tenant rollup of journal request records (the post-hoc twin
    of :func:`engine_tenant_usage`): engine request records carry
    ``tenant``/``device_ns``/``page_ns`` extras plus the derived
    ``queue_ms``/``ttft_ms``/``tpot_ms``/``e2e_ms``, so the chargeback
    table reconstructs from journals alone — exact to the token and
    the nanosecond."""
    tenants = {}
    for rec in records:
        s = _slot(tenants, rec.get("tenant") or DEFAULT_TENANT)
        s["requests"] += 1
        state = rec.get("state")
        if state == "FINISHED":
            s["completed"] += 1
        elif state == "CANCELLED":
            s["cancelled"] += 1
        if rec.get("preemptions"):
            s["preempted_requests"] += 1
            s["preemptions"] += int(rec["preemptions"])
        s["prompt_tokens"] += int(rec.get("prompt_tokens") or 0)
        s["decode_tokens"] += int(rec.get("output_tokens") or 0)
        s["device_ns"] += int(rec.get("device_ns") or 0)
        s["page_ns"] += int(rec.get("page_ns") or 0)
        lat = s["_lat"]
        for name in ("queue_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
            v = rec.get(name)
            if v is not None:
                lat[name].append(float(v))
    return _finalize(tenants)


def merge_tenant_rollups(rollups):
    """Merge per-replica/per-run tenant rollups: counters and int-ns
    integrals add exactly; percentile fields cannot be merged from
    percentiles and are dropped (re-derive them from pooled records
    via :func:`rollup_requests` when needed)."""
    out = {}
    for rollup in rollups:
        for t, s in (rollup or {}).items():
            dst = out.setdefault(t, dict(_ZERO))
            for k, v in s.items():
                if k in _ZERO:
                    dst[k] = dst.get(k, 0) + v
    return out


def tenant_slo_slices(run_dir, specs, duration_s=None):
    """Per-tenant SLO evaluation over a run's pooled journals: filter
    the pooled request records (and ``router.reject`` events) by
    tenant, then run the existing ``obs.slo.evaluate_run`` per slice —
    same ``SLOSpec`` objectives, one verdict per tenant."""
    from . import slo as _slo

    pooled = run_dir if isinstance(run_dir, dict) \
        else _slo.load_any(run_dir)
    by_tenant = {}
    for rec in pooled.get("requests") or []:
        by_tenant.setdefault(rec.get("tenant") or DEFAULT_TENANT,
                             []).append(rec)
    rejects = {}
    for ev in pooled.get("events") or []:
        if ev.get("kind") == "router.reject":
            rejects.setdefault(ev.get("tenant") or DEFAULT_TENANT,
                               []).append(ev)
    out = {}
    for tenant in sorted(set(by_tenant) | set(rejects)):
        sub = {"run_dir": pooled.get("run_dir"),
               "requests": by_tenant.get(tenant, []),
               "events": rejects.get(tenant, []),
               "runs": pooled.get("runs") or []}
        out[tenant] = _slo.evaluate_run(sub, specs,
                                        duration_s=duration_s)
    return out
