"""Declarative serving SLOs: error budgets, burn rates, and
multi-window multi-burn-rate alerting.

The windowing layer (``obs.timeseries``) answers "what happened over
the last N minutes"; this module answers the production question on
top of it: *are we currently violating our SLO, how fast are we
burning error budget, and which replica is responsible* — the signal
ROADMAP item 4's canary scoring depends on, and the serving-SLO
framing of the Gemma-on-TPU comparison (arXiv 2605.25645) treats as
the primary serving metric alongside the TTFT/TPOT decomposition.

Shape (Google SRE workbook, chapter 5, scaled to serve-fleet windows):

- An :class:`SLOSpec` declares one objective: a latency target
  ("99% of requests first-token within 250 ms"), an availability
  floor (``1 - rejects/requests >= 0.999``), or a goodput floor
  (tokens/s). The error budget is ``1 - target``.
- Each evaluation tick, the bad-event fraction over a window divided
  by the budget is that window's **burn rate**: burning at 1x spends
  exactly the budget over the budget period; 14.4x exhausts it ~14x
  early. An alert condition needs BOTH a long window (evidence) and a
  short window (fast clear) over the threshold: the fast page is
  burn >= 14.4 over 5m AND 30m, the slow warn burn >= 6 over 30m AND
  3h (:data:`DEFAULT_POLICIES`).
- Alerts latch: one ``slo.fire`` when the condition becomes true, one
  ``slo.clear`` when it stops — never a refire while latched. Both
  are ACTIVE-guarded journal events carrying per-replica attribution
  (the worst offender parsed from the same per-replica scrape the
  autoscaler reads), and tick ``slo.fire``/``slo.clear`` counters.

Everything is clock-injectable and caller-driven: the Router feeds
:meth:`SLOEvaluator.observe` from its EXISTING throttled autoscale
exposition (zero additional HTTP calls), tests feed hand-built
snapshots under a ManualClock and assert exact fire/clear instants.
With no evaluator installed nothing here runs — the zero-overhead
poison test pins that.

:func:`evaluate_run` is the post-hoc twin: the same spec evaluated
against a finished run dir's journal (``tools/slo_report.py`` and
``serve_bench --slo`` exit gates).
"""
from __future__ import annotations

import json
import os
import time

from . import journal as _journal
from . import metrics as _metrics
from . import timeseries as _timeseries
from .timeseries import WINDOWS

__all__ = [
    "SLOSpec", "AlertPolicy", "DEFAULT_POLICIES", "SLOEvaluator",
    "specs_from_dict", "parse_spec_arg", "evaluate_run", "load_any",
]


class SLOSpec:
    """One declarative objective.

    ``kind`` selects the math:

    - ``"latency"``: fraction of requests with ``metric`` (a latency
      histogram, ms) at or under ``threshold_ms`` must be >= ``target``
      (bad fraction = windowed fraction above the threshold).
    - ``"availability"``: ``1 - bad/total`` must be >= ``target``
      (bad/total are counter deltas — router rejects over submits).
    - ``"goodput"``: the windowed token rate must stay >= ``floor``
      tokens/s (binary bad fraction; budget still ``1 - target``).
    """

    KINDS = ("latency", "availability", "goodput")

    def __init__(self, name, kind, target=0.99, threshold_ms=None,
                 floor=None, metric=None, bad_metric=None,
                 good_metric=None):
        if kind not in self.KINDS:
            raise ValueError(f"unknown SLO kind {kind!r} "
                             f"(one of {self.KINDS})")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target!r} "
                f"for {name!r} — a target of 1.0 has zero error budget")
        if kind == "latency" and threshold_ms is None:
            raise ValueError(f"latency SLO {name!r} needs threshold_ms")
        if kind == "goodput" and floor is None:
            raise ValueError(f"goodput SLO {name!r} needs floor")
        self.threshold_ms = None if threshold_ms is None \
            else float(threshold_ms)
        self.floor = None if floor is None else float(floor)
        # candidate series names, registry-form first then the
        # exposition (scraped fleet) form — the store holds whichever
        # side of the process boundary fed it
        if kind == "latency":
            base = metric or ("serving.ttft_ms" if "ttft" in self.name
                              else "serving.tpot_ms")
            self.metrics = (base, "paddle_tpu_" +
                            base.replace(".", "_"))
        elif kind == "availability":
            bad = bad_metric or "serving.router.rejected"
            good = good_metric or "serving.router.dispatched"
            self.bad_metrics = (bad, "paddle_tpu_fleet_router_rejected")
            self.good_metrics = (good,
                                 "paddle_tpu_fleet_router_dispatched")
        else:
            base = metric or "serving.tokens_generated"
            self.metrics = (base, "paddle_tpu_" +
                            base.replace(".", "_"))

    @property
    def budget(self):
        """Error budget: the allowed bad-event fraction."""
        return 1.0 - self.target

    def describe(self):
        d = {"name": self.name, "kind": self.kind,
             "target": self.target}
        if self.threshold_ms is not None:
            d["threshold_ms"] = self.threshold_ms
        if self.floor is not None:
            d["floor"] = self.floor
        return d

    def __repr__(self):
        return f"SLOSpec({self.name!r}, {self.kind!r}, " \
               f"target={self.target})"


class AlertPolicy:
    """One multi-window burn-rate condition: fire when burn over BOTH
    the short and the long window is >= ``burn`` (short = fast clear,
    long = evidence); clear when either drops below."""

    def __init__(self, severity, short, long, burn):
        self.severity = str(severity)
        self.short = str(short)    # WINDOWS label, e.g. "5m"
        self.long = str(long)
        self.burn = float(burn)
        if WINDOWS[self.short] >= WINDOWS[self.long]:
            raise ValueError("short window must be < long window")

    def __repr__(self):
        return (f"AlertPolicy({self.severity!r}, {self.short}+"
                f"{self.long}, burn>={self.burn:g})")


# the SRE-workbook ladder scaled to serve-fleet windows (ISSUE 19):
# fast page at 14.4x over 5m+30m, slow warn at 6x over 30m+3h
DEFAULT_POLICIES = (AlertPolicy("page", "5m", "30m", 14.4),
                    AlertPolicy("warn", "30m", "3h", 6.0))


def specs_from_dict(d):
    """``SLOSpec`` list from the flat JSON objective form shared by
    ``serve_bench --slo`` and ``slo_report --spec``::

        {"ttft_p99_ms": 250, "tpot_p99_ms": 20,
         "availability": 0.999, "goodput_tps": 100}

    Latency keys take the threshold in ms (target 0.99 from the p99
    framing, or a ``{"threshold_ms": .., "target": ..}`` dict);
    ``availability`` takes the target fraction; ``goodput_tps`` the
    floor in tokens/s (target 0.99 of evaluation windows unless given
    as a dict)."""
    specs = []
    for key, val in dict(d).items():
        cfg = dict(val) if isinstance(val, dict) else {}
        if key in ("ttft_p99_ms", "tpot_p99_ms"):
            thr = cfg.pop("threshold_ms", None if cfg else val)
            specs.append(SLOSpec(key, "latency", threshold_ms=thr,
                                 target=cfg.pop("target", 0.99),
                                 **cfg))
        elif key == "availability":
            tgt = cfg.pop("target", None if cfg else val)
            specs.append(SLOSpec(key, "availability", target=tgt,
                                 **cfg))
        elif key == "goodput_tps":
            floor = cfg.pop("floor", None if cfg else val)
            specs.append(SLOSpec(key, "goodput", floor=floor,
                                 target=cfg.pop("target", 0.99),
                                 **cfg))
        else:
            raise KeyError(
                f"unknown SLO objective {key!r} (known: ttft_p99_ms, "
                "tpot_p99_ms, availability, goodput_tps)")
    return specs


def parse_spec_arg(arg):
    """CLI spec loader: inline JSON, or ``@path``/path to a JSON
    file."""
    s = str(arg).strip()
    if s.startswith("@"):
        s = s[1:]
    if not s.startswith("{") and os.path.exists(s):
        with open(s, encoding="utf-8") as f:
            s = f.read()
    return specs_from_dict(json.loads(s))


class SLOEvaluator:
    """Live windowed SLO evaluation + latched burn-rate alerting.

    Feed it one merged exposition (and/or the in-process registry) per
    tick via :meth:`observe`; read burn/budget gauges back through
    ``obs.export.slo_engine_lines`` (bitwise: the scraped gauge parses
    back equal to :meth:`burn_rate`'s float) and the live pane through
    :meth:`status` (the /statusz JSON).
    """

    def __init__(self, specs, clock=None, policies=None, store=None,
                 interval_s=None, include_registry=True, registry=None,
                 anomaly_engine=None):
        if isinstance(specs, dict):
            specs = specs_from_dict(specs)
        self.specs = list(specs)
        if not self.specs:
            raise ValueError("SLOEvaluator needs at least one SLOSpec")
        self.clock = clock if clock is not None else time.monotonic
        self.policies = tuple(policies if policies is not None
                              else DEFAULT_POLICIES)
        horizon = max(WINDOWS[p.long] for p in self.policies) * 2
        self.store = store if store is not None else \
            _timeseries.SeriesStore(
                interval_s=interval_s if interval_s is not None
                else 1.0, horizon_s=horizon, clock=self.clock)
        self.include_registry = bool(include_registry)
        self.registry = registry
        self.anomaly_engine = anomaly_engine
        # burn labels to compute: the policy windows plus the 1m pane
        labels = {"1m"}
        for p in self.policies:
            labels.add(p.short)
            labels.add(p.long)
        self.windows = tuple(sorted(labels, key=lambda w: WINDOWS[w]))
        self.burn = {}           # (objective, label) -> float|None
        self.budget_left = {}    # objective -> float|None
        self.replica_slo = {}    # replica -> {metric_qXX_ms: value}
        self._alerts = {}        # (objective, severity) -> state dict
        for spec in self.specs:
            for pol in self.policies:
                self._alerts[(spec.name, pol.severity)] = {
                    "objective": spec.name, "severity": pol.severity,
                    "active": False, "since": None, "fires": 0,
                    "clears": 0}
        self.alert_log = []      # bounded fire/clear history
        self._log_cap = 256
        self.ticks = 0
        self.last_t = None

    # -- signal math ---------------------------------------------------------
    def _first_series(self, names):
        for n in names:
            if self.store.kind(n) is not None:
                return n
        return None

    def bad_fraction(self, spec, window_s, now=None):
        """The windowed bad-event fraction for one objective, or None
        when the store holds no signal for it yet."""
        if spec.kind == "latency":
            name = self._first_series(spec.metrics)
            if name is None:
                return None
            bt = self.store.fraction_above(name, spec.threshold_ms,
                                           window_s, now=now)
            if bt is None:
                return None
            bad, total = bt
            return (bad / total) if total > 0 else 0.0
        if spec.kind == "availability":
            bad_name = self._first_series(spec.bad_metrics)
            good_name = self._first_series(spec.good_metrics)
            if bad_name is None or good_name is None:
                return None
            bad = self.store.counter_delta(bad_name, window_s, now=now)
            good = self.store.counter_delta(good_name, window_s,
                                            now=now)
            if bad is None or good is None:
                return None
            total = bad + good
            return (bad / total) if total > 0 else 0.0
        # goodput: binary — the window's token rate under the floor
        name = self._first_series(spec.metrics)
        if name is None:
            return None
        rate = self.store.counter_rate(name, window_s, now=now)
        if rate is None:
            return None
        return 1.0 if rate < spec.floor else 0.0

    def burn_rate(self, objective, window, now=None):
        """Burn over one window label ("5m"): bad fraction divided by
        the error budget (1.0 = spending exactly the budget). None
        without signal. Recomputed fresh so tests can probe arbitrary
        instants; :meth:`observe` caches the per-tick values in
        ``self.burn``."""
        spec = self._spec(objective)
        frac = self.bad_fraction(spec, WINDOWS[window], now=now)
        if frac is None:
            return None
        return frac / spec.budget

    def budget_remaining(self, objective, now=None):
        """1 - (budget consumed over the evaluator's full retained
        history); negative when overspent. None without signal."""
        spec = self._spec(objective)
        frac = self.bad_fraction(spec, float("inf"), now=now)
        if frac is None:
            return None
        return 1.0 - frac / spec.budget

    def _spec(self, objective):
        for s in self.specs:
            if s.name == objective:
                return s
        raise KeyError(f"unknown objective {objective!r}")

    # -- the tick ------------------------------------------------------------
    def observe(self, text=None, registry=None, now=None, extra=None):
        """One evaluation tick: snapshot the inputs into the store,
        recompute burn/budget, run the alert state machines (journal
        ``slo.fire``/``slo.clear``, tick ``slo.*`` counters), feed the
        serving anomaly detectors. ``extra`` (a dict) is folded into
        the anomaly record verbatim — the router's per-tenant fairness
        fields (``obs.usage.fairness_record``) ride the same tick the
        latency detectors read. Returns the alert transitions
        (``slo.fire``/``slo.clear`` dicts) of this tick."""
        now = self.clock() if now is None else float(now)
        snap = {}
        if self.include_registry or registry is not None:
            snap.update(_timeseries.registry_snapshot(
                registry if registry is not None else self.registry))
        if text is not None:
            if isinstance(text, dict):
                snap.update(text)
            else:
                snap.update(_timeseries.exposition_snapshot(text))
                self._note_replicas(text)
        self.store.observe(snap, now=now)
        self.ticks += 1
        self.last_t = now
        _metrics.counter("slo.ticks").inc()

        for spec in self.specs:
            for label in self.windows:
                self.burn[(spec.name, label)] = \
                    self.burn_rate(spec.name, label, now=now)
            self.budget_left[spec.name] = \
                self.budget_remaining(spec.name, now=now)

        transitions = []
        for spec in self.specs:
            for pol in self.policies:
                transitions.extend(
                    self._drive_alert(spec, pol, now))
        self._observe_anomalies(now, extra=extra)
        return transitions

    def _drive_alert(self, spec, pol, now):
        st = self._alerts[(spec.name, pol.severity)]
        bs = self.burn.get((spec.name, pol.short))
        bl = self.burn.get((spec.name, pol.long))
        firing = bs is not None and bl is not None and \
            bs >= pol.burn and bl >= pol.burn
        out = []
        if firing and not st["active"]:
            st["active"] = True
            st["since"] = now
            st["fires"] += 1
            worst, worst_value = self._worst_offender(spec)
            rec = {"at": now, "kind": "slo.fire",
                   "objective": spec.name, "severity": pol.severity,
                   "burn_short": bs, "burn_long": bl,
                   "window_short": pol.short, "window_long": pol.long,
                   "threshold": pol.burn, "worst_replica": worst,
                   "worst_value": worst_value}
            self._log(rec)
            out.append(rec)
            _metrics.counter("slo.fire").inc()
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event(
                    "slo.fire", at=now, objective=spec.name,
                    severity=pol.severity, burn_short=bs, burn_long=bl,
                    window_short=pol.short, window_long=pol.long,
                    threshold=pol.burn, worst_replica=worst,
                    worst_value=worst_value,
                    budget_remaining=self.budget_left.get(spec.name))
        elif st["active"] and not firing:
            st["active"] = False
            st["clears"] += 1
            rec = {"at": now, "kind": "slo.clear",
                   "objective": spec.name, "severity": pol.severity,
                   "burn_short": bs, "burn_long": bl,
                   "window_short": pol.short, "window_long": pol.long,
                   "threshold": pol.burn,
                   "since": st["since"]}
            st["since"] = None
            self._log(rec)
            out.append(rec)
            _metrics.counter("slo.clear").inc()
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event(
                    "slo.clear", at=now, objective=spec.name,
                    severity=pol.severity, burn_short=bs, burn_long=bl,
                    window_short=pol.short, window_long=pol.long,
                    threshold=pol.burn,
                    budget_remaining=self.budget_left.get(spec.name))
        return out

    def _log(self, rec):
        if len(self.alert_log) < self._log_cap:
            self.alert_log.append(rec)

    def _note_replicas(self, text):
        """Cache the per-replica SLO gauges from the tick's scrape
        (the attribution table statusz renders and the worst-offender
        lookup reads) — same signal surface as the autoscaler's
        ``signals_from_scrape``."""
        from ..serving.fleet.autoscale import per_replica_slo_from_scrape

        try:
            per = per_replica_slo_from_scrape(text)
        except Exception:
            return
        if per:
            self.replica_slo = per

    def _worst_offender(self, spec):
        """Worst replica for a latency objective: the argmax of the
        per-replica p99 gauge from the last scrape (pooled fleet
        percentiles don't attribute — the per-replica scrape does).
        None for fleet-scoped objectives (availability/goodput)."""
        if spec.kind != "latency" or not self.replica_slo:
            return None, None
        key = "ttft_p99_ms" if "ttft" in spec.name else "tpot_p99_ms"
        worst, worst_value = None, None
        for rep, vals in sorted(self.replica_slo.items()):
            v = vals.get(key)
            if v is None:
                continue
            if worst_value is None or v > worst_value:
                worst, worst_value = rep, v
        return worst, worst_value

    def _observe_anomalies(self, now, extra=None):
        """Feed the serving anomaly detectors one windowed record:
        TTFT p99 over the 1m pane, the per-token latency implied by
        the 1m token rate (``throughput_drop``'s serving signal), and
        any caller-supplied ``extra`` fields (``tenant_hog``'s
        fairness signal)."""
        if self.anomaly_engine is None:
            return
        rec = {"step": self.ticks}
        if extra:
            rec.update(extra)
        for spec in self.specs:
            if spec.kind != "latency":
                continue
            name = self._first_series(spec.metrics)
            if name is None:
                continue
            p99 = self.store.percentile(name, 99, WINDOWS["1m"],
                                        now=now)
            if p99 is not None and "ttft" in spec.name:
                rec["ttft_ms"] = p99
        for spec in self.specs:
            if spec.kind != "goodput":
                continue
            name = self._first_series(spec.metrics)
            if name is None:
                continue
            rate = self.store.counter_rate(name, WINDOWS["1m"],
                                           now=now)
            if rate and rate > 0:
                rec["step_ms"] = 1e3 / rate
        if len(rec) <= 1:
            return
        for fired in self.anomaly_engine.observe(rec):
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event("anomaly.serving",
                                      name=fired["name"], at=now,
                                      detail=fired["detail"])

    # -- introspection -------------------------------------------------------
    def active_alerts(self):
        return [dict(st) for st in self._alerts.values()
                if st["active"]]

    def status(self):
        """The live SLO pane as plain data (the /statusz JSON body's
        ``slo`` section)."""
        objectives = []
        for spec in self.specs:
            objectives.append({
                **spec.describe(),
                "burn": {label: self.burn.get((spec.name, label))
                         for label in self.windows},
                "budget_remaining": self.budget_left.get(spec.name),
                "alerts": [
                    {"severity": pol.severity,
                     "active": self._alerts[(spec.name,
                                             pol.severity)]["active"],
                     "since": self._alerts[(spec.name,
                                            pol.severity)]["since"],
                     "burn_threshold": pol.burn,
                     "windows": f"{pol.short}+{pol.long}"}
                    for pol in self.policies],
            })
        return {"last_t": self.last_t, "ticks": self.ticks,
                "objectives": objectives,
                "active_alerts": self.active_alerts(),
                "replica_slo": {str(k): dict(v) for k, v in
                                sorted(self.replica_slo.items())},
                "alert_log": list(self.alert_log)}

    def journal_summary(self):
        """One ``slo.summary`` event with the final per-objective
        truth (fires/clears/budget) — the record ``tools/
        slo_report.py`` renders; last wins."""
        if _journal.ACTIVE is None:
            return
        per = {}
        for spec in self.specs:
            fires = sum(self._alerts[(spec.name, p.severity)]["fires"]
                        for p in self.policies)
            clears = sum(
                self._alerts[(spec.name, p.severity)]["clears"]
                for p in self.policies)
            per[spec.name] = {
                "budget_remaining": self.budget_left.get(spec.name),
                "fires": fires, "clears": clears,
                "burn_5m": self.burn.get((spec.name, "5m"))}
        _journal.ACTIVE.event("slo.summary", ticks=self.ticks,
                              objectives=per)


# -- post-hoc evaluation ------------------------------------------------------


def load_any(run_dir):
    """Pool every journal under ``run_dir`` (top-level single-engine,
    ``router/``, ``rank_NN/``) into one ``{requests, events}`` view —
    the loader shared by :func:`evaluate_run` and
    ``tools/slo_report.py`` so single-engine bench runs and routed
    fleet runs evaluate identically."""
    from . import fleet as _fleet

    run_dir = str(run_dir)
    requests, events, runs = [], [], []
    top = os.path.join(run_dir, _journal.JOURNAL_FILE)
    if os.path.isfile(top):
        runs.append(_fleet.load_journal(run_dir))
    rd = _fleet.router_dir(run_dir)
    if rd:
        runs.append(_fleet.load_journal(rd))
    for _rank, path in sorted(_fleet.rank_dirs(run_dir).items()):
        runs.append(_fleet.load_journal(path))
    if not runs:
        raise FileNotFoundError(
            f"no journals under {run_dir!r} (looked for "
            f"{_journal.JOURNAL_FILE}, router/, rank_NN/)")
    for run in runs:
        requests += run.get("requests") or []
        events += run.get("events") or []
    return {"run_dir": run_dir, "requests": requests,
            "events": events, "runs": runs}


def evaluate_run(run_dir, specs, duration_s=None):
    """Evaluate a finished run's journal against the spec: exact
    nearest-rank percentiles over the pooled per-request records
    (``fleet.request_summary`` — per-replica percentiles don't
    average), availability from reject events over submits, goodput
    from output tokens over the serving-clock span (or an explicit
    ``duration_s``). Returns ``{"objectives": [...], "violations":
    [names], "summary": ...}`` — an objective without signal reports
    ``ok=None`` and does NOT count as a violation."""
    from . import fleet as _fleet

    if isinstance(specs, dict):
        specs = specs_from_dict(specs)
    pooled = run_dir if isinstance(run_dir, dict) else \
        load_any(run_dir)
    summary = _fleet.request_summary(
        {"requests": pooled["requests"]})
    events = pooled["events"]
    rejects = sum(1 for e in events
                  if e.get("kind") == "router.reject")
    requests = len(pooled["requests"])
    tokens = sum(int(r.get("output_tokens") or 0)
                 for r in pooled["requests"])
    if duration_s is None:
        arr = [r["arrival_t"] for r in pooled["requests"]
               if isinstance(r.get("arrival_t"), (int, float))]
        fin = [r["finish_t"] for r in pooled["requests"]
               if isinstance(r.get("finish_t"), (int, float))]
        if arr and fin and max(fin) > min(arr):
            duration_s = max(fin) - min(arr)

    objectives, violations = [], []
    for spec in specs:
        row = spec.describe()
        value, ok = None, None
        if spec.kind == "latency":
            key = ("ttft_ms_p99" if "ttft" in spec.name
                   else "tpot_ms_p99")
            value = (summary or {}).get(key)
            if value is not None:
                ok = value <= spec.threshold_ms
        elif spec.kind == "availability":
            total = requests + rejects
            if total > 0:
                value = 1.0 - rejects / total
                ok = value >= spec.target
        else:  # goodput
            if duration_s and duration_s > 0 and tokens:
                value = tokens / duration_s
                ok = value >= spec.floor
        row["value"] = value
        row["ok"] = ok
        objectives.append(row)
        if ok is False:
            violations.append(spec.name)
    return {"objectives": objectives, "violations": violations,
            "summary": summary,
            "rejects": rejects, "requests": requests,
            "output_tokens": tokens, "duration_s": duration_s}
