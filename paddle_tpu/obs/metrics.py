"""Process-wide metrics registry: Counters, Gauges, fixed-bucket Histograms.

The reference exposes runtime health through the C++ profiler's per-op
records and assorted VLOG counters; here every subsystem ticks named
instruments in one registry instead, and anything — the report CLI, a
test, a serving health endpoint — reads a consistent ``snapshot()``.

Design constraints (they shape the whole module):

- **Cheap when ignored.** An ``inc()``/``observe()`` is a lock-guarded
  int add on the host — no allocation beyond the first registration, no
  device sync, nothing proportional to data size. Instrument objects are
  interned by name, so hot paths hold a direct reference and skip the
  registry dict entirely.
- **Thread-safe.** DataLoader workers, the chaos supervisor, and the
  train loop all tick concurrently; every mutation takes the
  instrument's own lock (never the registry lock), so contention is
  per-instrument.
- **Reset keeps registrations.** ``reset()`` zeroes values but leaves
  the instruments interned — references cached by hot paths stay live,
  which is what makes per-test resets safe.
"""
from __future__ import annotations

import bisect
import math
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "reset",
    "exact_percentile", "DEFAULT_MS_BUCKETS", "WIDE_MS_BUCKETS",
]


def exact_percentile(xs, q):
    """Exact q-th percentile by nearest rank over raw samples (the
    complement of Histogram's bounded-bucket interpolation, for readers
    that kept every sample — per-request journal records, bench traces).
    One definition shared by tools/run_report.py and
    tools/serve_bench.py so their p50/p99 columns stay comparable."""
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[i]

# upper bounds (ms) covering µs-scale op dispatch through multi-second
# XLA compiles; +inf is implicit as the overflow bucket
DEFAULT_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                      50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0, 30000.0)

# the default set tops out at 30s — fine for steps and compiles, but
# whole-gang events (elastic resume = failure detection -> every worker
# beating again, which includes process spawn + backend init + a
# checkpoint load) live in the seconds-to-minutes band; this extension
# keeps percentile resolution out to 10 minutes instead of clamping
# everything past 30s into the overflow bucket
WIDE_MS_BUCKETS = DEFAULT_MS_BUCKETS + (60000.0, 120000.0, 300000.0,
                                        600000.0)


class Counter:
    """Monotonic count (events, hits, retries)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def _snapshot(self):
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-set level (queue depth, cache size, active workers)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def _snapshot(self):
        return self._value

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


class Histogram:
    """Fixed-bucket distribution (latencies, wait times).

    Buckets are upper bounds chosen at registration and never change, so
    ``observe()`` is a bisect + two int adds — no per-sample storage, a
    bounded footprint no matter how many billions of steps tick it.
    Percentiles come from linear interpolation inside the owning bucket
    (exact enough for dashboards; tests wanting exact quantiles keep raw
    samples themselves, as ``utils.profiler.StepTimer`` does).
    """

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum", "_min",
                 "_max", "_lock")

    def __init__(self, name, buckets=DEFAULT_MS_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram buckets must be sorted ascending "
                             f"upper bounds, got {buckets!r}")
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +1 overflow
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Approximate q-th percentile (q in [0, 100]) by interpolating
        within the bucket holding the rank; the overflow bucket clamps to
        the observed max."""
        with self._lock:
            total = self._count
            if total == 0:
                return None
            rank = (q / 100.0) * total
            seen = 0.0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if seen + c >= rank:
                    hi = self.buckets[i] if i < len(self.buckets) \
                        else self._max
                    lo = self.buckets[i - 1] if i > 0 else \
                        min(self._min, hi)
                    frac = (rank - seen) / c
                    v = lo + (hi - lo) * max(0.0, min(1.0, frac))
                    return float(min(max(v, self._min), self._max))
                seen += c
            return float(self._max)

    def bucket_counts(self):
        """Consistent ``(buckets, counts, count, sum)`` snapshot —
        ``counts`` has one extra overflow slot past the last bound. The
        raw-distribution accessor Prometheus exposition needs
        (``obs.export`` turns it into cumulative ``_bucket`` series);
        ``_snapshot()`` stays the human-facing percentile view."""
        with self._lock:
            return (self.buckets, tuple(self._counts), self._count,
                    self._sum)

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf

    def _snapshot(self):
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            snap = {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max,
                    "mean": self._sum / self._count}
        snap["p50"] = self.percentile(50)
        snap["p90"] = self.percentile(90)
        snap["p99"] = self.percentile(99)
        return snap

    def __repr__(self):
        return f"Histogram({self.name}, count={self._count})"


class Registry:
    """Name -> instrument interning. One process-wide instance
    (``REGISTRY``) backs the module-level helpers; private registries
    exist only for tests that must not see global state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict = {}

    def _get(self, name, cls, *args):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"instrument {name!r} is a {type(inst).__name__}, "
                    f"requested as {cls.__name__}")
            return inst

    def counter(self, name) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name, buckets=DEFAULT_MS_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets)

    def names(self):
        with self._lock:
            return sorted(self._instruments)

    def get(self, name):
        with self._lock:
            return self._instruments.get(name)

    def snapshot(self):
        """{name: value} for counters/gauges, {name: stats-dict} for
        histograms — a plain-data copy safe to json.dumps."""
        with self._lock:
            items = list(self._instruments.items())
        return {name: inst._snapshot() for name, inst in sorted(items)}

    def reset(self):
        """Zero every instrument, KEEPING registrations (cached hot-path
        references stay valid)."""
        with self._lock:
            items = list(self._instruments.values())
        for inst in items:
            inst._reset()


REGISTRY = Registry()


def counter(name) -> Counter:
    return REGISTRY.counter(name)


def gauge(name) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name, buckets=DEFAULT_MS_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, buckets)


def snapshot():
    return REGISTRY.snapshot()


def reset():
    REGISTRY.reset()
