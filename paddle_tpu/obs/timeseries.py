"""Fixed-interval rolling windows over the metrics plane.

The registry (``obs.metrics``) and the Prometheus exposition
(``obs.export``) are CUMULATIVE views: a counter only ever grows, a
histogram's ``_bucket`` series only ever fills. Production questions
are WINDOWED: "what is the reject rate over the last 5 minutes", "what
is TTFT p99 over the last half hour" — the inputs the SLO engine
(``obs.slo``) burns error budget against and ROADMAP item 4's canary
scoring compares across releases.

This module is that windowing layer, and nothing else:

- :class:`SeriesStore` keeps a bounded ring of ``(t, value)`` samples
  per series — counters and gauges as floats, histograms as cumulative
  bucket-count tuples — and answers window queries by DIFFERENCING two
  ring entries: ``counter_delta``/``counter_rate``, ``gauge_last``/
  ``gauge_delta``, ``hist_window`` (per-bucket count deltas) and
  ``percentile``/``fraction_above`` derived from them. Memory is
  bounded by ``horizon_s / interval_s`` samples per series no matter
  how long the process lives.
- Two snapshot builders feed it with the SAME shape:
  :func:`registry_snapshot` (in-process ``obs.metrics`` instruments)
  and :func:`exposition_snapshot` (a scraped/merged Prometheus text —
  the multi-process fleet path), so a window query does not care which
  side of a process boundary the samples came from.
- Every timestamp comes from the caller (or an injectable ``clock``),
  so tests drive a ``ManualClock`` and the window math is EXACT — the
  property the burn-rate acceptance fixtures rest on.

Pull-only and caller-driven: nothing here samples on its own, nothing
runs unless ``observe()``/``sample()`` is called — the zero-overhead
hook contract holds trivially (the poison test pins it).
"""
from __future__ import annotations

import bisect
import math
import time
from collections import deque

from . import metrics as _metrics
from .metrics import Counter, Gauge, Histogram

__all__ = [
    "SeriesStore", "registry_snapshot", "exposition_snapshot",
    "percentile_from_buckets", "WINDOWS",
]

# the canonical window ladder (label -> seconds): the 1m/5m/30m panes
# the statusz tables render and the 5m/30m/3h pairs the SRE-style
# burn-rate policies in obs.slo are built from
WINDOWS = {"1m": 60.0, "5m": 300.0, "30m": 1800.0, "3h": 10800.0}


def percentile_from_buckets(buckets, counts, q):
    """Interpolated q-th percentile from per-bucket counts (``counts``
    has one overflow slot past the last bound) — the windowed twin of
    ``Histogram.percentile``, with the window's bucket deltas standing
    in for the instrument's lifetime counts. Without min/max the first
    bucket interpolates from 0 and the overflow clamps to the last
    finite bound. Returns None on an empty window."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = (q / 100.0) * total
    seen = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if seen + c >= rank:
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            lo = buckets[i - 1] if i > 0 else min(0.0, hi)
            frac = (rank - seen) / c
            return float(lo + (hi - lo) * max(0.0, min(1.0, frac)))
        seen += c
    return float(buckets[-1])


def _cumulative(counts):
    out = []
    cum = 0
    for c in counts:
        cum += c
        out.append(cum)
    return tuple(out)


def registry_snapshot(registry=None):
    """One ``{name: (type, payload)}`` snapshot of the in-process
    metrics registry: counters/gauges as floats, histograms as
    ``(buckets, cumulative_counts, count, sum)`` — cumulative counts
    carry the overflow slot, so ``cumulative_counts[-1] == count``."""
    reg = registry if registry is not None else _metrics.REGISTRY
    out = {}
    for name in reg.names():
        inst = reg.get(name)
        if isinstance(inst, Counter):
            out[name] = ("counter", float(inst.value))
        elif isinstance(inst, Histogram):
            buckets, counts, count, total = inst.bucket_counts()
            out[name] = ("histogram",
                         (buckets, _cumulative(counts), count, total))
        elif isinstance(inst, Gauge):
            out[name] = ("gauge", float(inst.value))
    return out


def exposition_snapshot(text):
    """The same snapshot shape from Prometheus exposition text (one
    exporter's render, or a ``merge_expositions`` fusion of a whole
    fleet) — so windowing over scraped out-of-process replicas is the
    identical code path as windowing over the local registry.

    Series names keep their exposition form including labels
    (``paddle_tpu_serving_slo_ttft_ms{replica="0",q="p99"}``);
    histogram families collapse their ``_bucket``/``_sum``/``_count``
    series back into ONE histogram payload under the family name.
    Samples without a ``# TYPE`` declaration default to gauge."""
    types = {}
    samples = []   # (key, value-string) in exposition order
    for line in str(text).splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types.setdefault(parts[2], parts[3])
            continue
        if line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        if key:
            samples.append((key, val))
    out = {}
    hists = {}     # family -> {"le": [(bound, cum)], "sum": s, "count": n}
    for key, val in samples:
        family = key.split("{", 1)[0]
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if family.endswith(suffix) and \
                    types.get(family[:-len(suffix)]) == "histogram":
                base = (family[:-len(suffix)], suffix)
                break
        if base is not None:
            fam, suffix = base
            h = hists.setdefault(fam, {"le": [], "sum": 0.0,
                                       "count": 0})
            try:
                fval = float(val)
            except ValueError:
                continue
            if suffix == "_bucket":
                m = key.partition("{")[2]
                le = None
                for part in m.rstrip("}").split(","):
                    k, _, v = part.partition("=")
                    if k.strip() == "le":
                        le = v.strip().strip('"')
                if le is None:
                    continue
                try:
                    bound = float(le)
                except ValueError:
                    continue
                if not math.isfinite(bound):
                    # the +Inf bucket is the overflow slot, which the
                    # payload derives from _count (float("+Inf") parses
                    # fine, so this must be an explicit skip)
                    continue
                h["le"].append((bound, fval))
            elif suffix == "_sum":
                h["sum"] = fval
            else:
                h["count"] = int(fval)
            continue
        try:
            fval = float(val)
        except ValueError:
            continue
        typ = types.get(family, "gauge")
        if typ == "counter":
            out[key] = ("counter", fval)
        else:
            out[key] = ("gauge", fval)
    for fam, h in hists.items():
        pairs = sorted(h["le"])
        buckets = tuple(b for b, _ in pairs)
        cum = tuple(int(c) for _, c in pairs) + (int(h["count"]),)
        out[fam] = ("histogram", (buckets, cum, int(h["count"]),
                                  float(h["sum"])))
    return out


class _Ring:
    """Bounded ring of ``(t, payload)`` samples, timestamps
    monotonically appended."""

    __slots__ = ("samples",)

    def __init__(self, cap):
        self.samples = deque(maxlen=cap)

    def append(self, t, payload):
        self.samples.append((t, payload))

    def at_or_before(self, t):
        """Latest sample with timestamp <= t; falls back to the OLDEST
        retained sample when the window predates the ring (a partial
        window reads what history exists rather than nothing)."""
        best = None
        for ts, payload in self.samples:
            if ts <= t:
                best = (ts, payload)
            else:
                break
        if best is None and self.samples:
            return self.samples[0]
        return best

    def last(self):
        return self.samples[-1] if self.samples else None


class SeriesStore:
    """Bounded rings of metric samples + window queries over them.

    ``interval_s`` is the nominal sampling cadence ``sample()``
    enforces (``observe()`` records unconditionally — tests and the
    SLO evaluator own their cadence); ``horizon_s`` bounds retention.
    All query ``now`` defaults resolve to the newest sample time, so a
    ManualClock test never races a wall clock.
    """

    def __init__(self, interval_s=1.0, horizon_s=3 * 3600.0,
                 clock=None):
        self.interval_s = float(interval_s)
        self.horizon_s = float(horizon_s)
        self.clock = clock if clock is not None else time.monotonic
        self._cap = max(2, int(self.horizon_s / self.interval_s) + 2)
        self._rings = {}      # name -> _Ring
        self._kinds = {}      # name -> "counter"|"gauge"|"histogram"
        self._last_t = None

    # -- feeding -------------------------------------------------------------
    def observe(self, snapshot, now=None):
        """Record one snapshot (``registry_snapshot`` /
        ``exposition_snapshot`` shape, or several merged) at ``now``."""
        now = self.clock() if now is None else float(now)
        for name, (kind, payload) in snapshot.items():
            ring = self._rings.get(name)
            if ring is None:
                ring = self._rings[name] = _Ring(self._cap)
                self._kinds[name] = kind
            ring.append(now, payload)
        self._last_t = now
        return now

    def sample(self, snapshot_fn, now=None):
        """Cadence-gated feed: calls ``snapshot_fn()`` and records it
        only when ``interval_s`` has elapsed since the last sample —
        the cheap form a polling loop calls every iteration. Returns
        the sample time, or None when not yet due."""
        now = self.clock() if now is None else float(now)
        if self._last_t is not None and \
                now < self._last_t + self.interval_s:
            return None
        return self.observe(snapshot_fn(), now=now)

    @property
    def last_t(self):
        return self._last_t

    def names(self):
        return sorted(self._rings)

    def kind(self, name):
        return self._kinds.get(name)

    # -- window plumbing -----------------------------------------------------
    def _pair(self, name, window_s, now=None):
        ring = self._rings.get(name)
        if ring is None or not ring.samples:
            return None
        now = self._last_t if now is None else float(now)
        new = ring.at_or_before(now)
        old = ring.at_or_before(now - float(window_s))
        if new is None or old is None:
            return None
        return old, new

    # -- counters ------------------------------------------------------------
    def counter_delta(self, name, window_s, now=None):
        """Increment over the window (clamped at 0: a reset/restart
        shows as a flat window, not a negative rate)."""
        pair = self._pair(name, window_s, now)
        if pair is None:
            return None
        (_, v0), (_, v1) = pair
        return max(0.0, float(v1) - float(v0))

    def counter_rate(self, name, window_s, now=None):
        """Increments per second over the window (None when the window
        holds fewer than two distinct samples)."""
        pair = self._pair(name, window_s, now)
        if pair is None:
            return None
        (t0, v0), (t1, v1) = pair
        if t1 <= t0:
            return None
        return max(0.0, float(v1) - float(v0)) / (t1 - t0)

    # -- gauges --------------------------------------------------------------
    def gauge_last(self, name, now=None):
        ring = self._rings.get(name)
        if ring is None:
            return None
        now = self._last_t if now is None else float(now)
        s = ring.at_or_before(now)
        return None if s is None else float(s[1])

    def gauge_delta(self, name, window_s, now=None):
        """Trend: newest minus window-start value (signed)."""
        pair = self._pair(name, window_s, now)
        if pair is None:
            return None
        (_, v0), (_, v1) = pair
        return float(v1) - float(v0)

    # -- histograms ----------------------------------------------------------
    def hist_window(self, name, window_s, now=None):
        """``(buckets, counts, count, sum)`` for observations INSIDE
        the window: per-bucket deltas of the cumulative rings (counts
        carries the overflow slot, like ``Histogram.bucket_counts``).
        None when the series is absent or the window is empty of
        samples."""
        pair = self._pair(name, window_s, now)
        if pair is None:
            return None
        (_, h0), (_, h1) = pair
        b0, c0, n0, s0 = h0
        b1, c1, n1, s1 = h1
        if b0 != b1:       # bucket layout changed (restart): no delta
            c0, n0, s0 = (0,) * len(c1), 0, 0.0
        counts = tuple(max(0, int(a) - int(b))
                       for a, b in zip(c1, c0))
        # de-cumulate: ring payloads are cumulative-within-snapshot
        flat = []
        prev = 0
        for c in counts:
            flat.append(max(0, c - prev))
            prev = c
        return (b1, tuple(flat), max(0, int(n1) - int(n0)),
                float(s1) - float(s0))

    def percentile(self, name, q, window_s, now=None):
        """Windowed interpolated percentile over a histogram series
        (p50/p99 over the last 1m/5m/30m — the statusz table cell)."""
        win = self.hist_window(name, window_s, now)
        if win is None:
            return None
        buckets, counts, _count, _sum = win
        return percentile_from_buckets(buckets, counts, q)

    def fraction_above(self, name, threshold, window_s, now=None):
        """Fraction of the window's observations STRICTLY above
        ``threshold`` — the latency-SLO bad-event fraction. Exact when
        ``threshold`` equals a bucket upper bound (the histogram's
        ``observe`` bisects left, so a sample equal to a bound lands in
        that bound's bucket); between bounds it is conservative,
        counting the whole straddling bucket as above. Returns
        ``(bad, total)`` so callers can pool windows, or None on an
        empty/absent window."""
        win = self.hist_window(name, window_s, now)
        if win is None:
            return None
        buckets, counts, total, _sum = win
        if total <= 0:
            return (0.0, 0.0)
        i = bisect.bisect_left(buckets, float(threshold))
        if i < len(buckets) and buckets[i] == float(threshold):
            i += 1
        bad = sum(counts[i:])
        return (float(bad), float(total))
