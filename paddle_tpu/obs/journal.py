"""RunJournal: an append-only JSONL flight recorder for one training run.

PR 3 gave the process instruments (``obs.metrics`` / ``obs.trace``);
this ties them to *a run*: a durable `journal.jsonl` under a run
directory (env ``PADDLE_TPU_RUN_DIR`` or an explicit path) holding

- one ``run_start`` header (backend, device count, env knobs, argv),
- a ``step`` record per training step (loss/fetches summary, step_ms,
  examples/sec, dataloader queue depth + consumer-wait delta, jit-cache
  hit/miss delta, FLOPs when known),
- discrete ``event`` records (compile, checkpoint save/load/fallback,
  resilience retry/skip/rollback/degrade, chaos activation,
  dataloader worker restarts),
- ``anomaly`` records from the detectors (``obs.anomaly``) evaluated
  on every step, and
- one ``run_end`` summary: MFU/goodput accounting (``obs.mfu``).

Write path: records buffer in memory (bounded) and flush every
``flush_every`` records or ``flush_interval_s`` seconds — a line is
written whole, so a reader never sees a torn record from a clean
writer. The file rotates at ``max_bytes`` (``journal.jsonl`` is always
the live tail; rotated parts are ``journal.<n>.jsonl``). On interpreter
exit (``atexit``) an unclosed journal flushes and writes its summary;
an exception exiting the ``with`` block (or an explicit
``postmortem()``) additionally dumps ``postmortem.json`` — the last-K
step records, recent events, the exception, a metrics snapshot — and a
Chrome trace when span tracing is on.

Hook contract (the established chaos/obs pattern): every production
hook is ``if _journal.ACTIVE is not None: ...`` — with no journal
configured the step path performs a single None check, no call, no
allocation, no host sync. With a journal active, summarizing an eager
loss costs one scalar device->host read per step (standard logging
cost; the static Executor path summarizes already-fetched host arrays,
and its lazy/async fetch paths — ``return_numpy=False`` /
``fetch_async=True`` — journal metadata-only summaries so logging
never re-introduces the host sync the caller opted out of). A fused
``run_steps`` window journals as ONE record with ``steps_fused=K``.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from collections import deque

from . import lockdep as _lockdep
from . import metrics as _metrics
from . import trace as _trace
from .anomaly import AnomalyEngine, default_detectors
from .mfu import MFUAccounting, peak_flops

__all__ = ["RunJournal", "ACTIVE", "start_run", "end_run", "active",
           "JOURNAL_FILE", "POSTMORTEM_FILE", "TRACE_FILE",
           "RANK_ENV", "SUPERVISOR_DIR", "ROUTER_DIR", "rank_subdir",
           "env_rank"]

JOURNAL_FILE = "journal.jsonl"
POSTMORTEM_FILE = "postmortem.json"
TRACE_FILE = "trace.json"
# the rank identity a gang launcher (resilience.elastic.GangSupervisor,
# dist.launch) hands each worker, alongside a per-rank run dir
RANK_ENV = "PADDLE_TPU_RANK"
# where a gang supervisor's own events land under the fleet run dir —
# ONE constant shared by the writer (resilience.elastic) and the reader
# (obs.fleet); a rename on either side would silently orphan the record
SUPERVISOR_DIR = "supervisor"
# likewise for the serve-fleet router's own journal (writer:
# serving.fleet.Router / drill; reader: obs.fleet.router_summary)
ROUTER_DIR = "router"

# The active journal every hook checks (mirrors resilience.inject.ACTIVE:
# None => hooks are a single None check and nothing else).
ACTIVE = None


def active():
    return ACTIVE


def rank_subdir(rank):
    """One naming convention for per-rank journal dirs
    (``rank_00``, ``rank_01``, ...): the writer (RunJournal), the gang
    launchers and the reader (``obs.fleet``) must all agree on it."""
    return f"rank_{int(rank):02d}"


def env_rank(env=None):
    """This process's rank from ``PADDLE_TPU_RANK``, or None outside a
    supervised gang (or on an unparseable value — identity must never
    break journaling)."""
    v = (env if env is not None else os.environ).get(RANK_ENV)
    if v in (None, ""):
        return None
    try:
        return int(v)
    except ValueError:
        return None


def _env_knobs():
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(("PADDLE_TPU_", "JAX_", "XLA_"))}


def _backend_info():
    """Backend identity WITHOUT forcing backend creation: probing
    ``jax.devices()`` before the user's own config/init would pin the
    platform (and on a wedged TPU tunnel, block) — unacceptable as an
    import/start side effect. An uninitialized backend reports None;
    the journal re-probes lazily once a step has actually executed
    (by which point the backend necessarily exists)."""
    try:
        import jax

        try:
            from jax._src import xla_bridge as _xb

            if hasattr(_xb, "_backends") and not _xb._backends:
                return {"backend": None, "ndev": None,
                        "backend_note": "jax backend not initialized"}
        except ImportError:
            pass  # private layout moved: fall through to the probe
        devs = jax.devices()
        kinds = {}
        for d in devs:
            kinds[d.device_kind] = kinds.get(d.device_kind, 0) + 1
        out = {"backend": devs[0].platform,
               "platform": devs[0].platform,
               "ndev": len(devs), "device_count": len(devs),
               "device_kind": devs[0].device_kind,
               "device_kinds": kinds}
        if len(devs) <= 64:  # keep the event record bounded on big pods
            out["devices"] = [
                {"id": int(d.id), "kind": d.device_kind,
                 "process": int(getattr(d, "process_index", 0))}
                for d in devs]
        return out
    except Exception as e:  # journal must work before/without a backend
        return {"backend": None, "ndev": None,
                "backend_error": f"{type(e).__name__}: {e}"}


def _summarize_value(v, sync=True):
    """Small, JSON-safe summary of one fetched value: size-1 numerics
    inline as a float, everything else as shape/dtype metadata. Only a
    SIZE-1 value is ever materialized (one scalar read); larger arrays
    are summarized from metadata alone, so a lazy device fetch
    (``return_numpy=False``) is never synced wholesale.

    ``sync=False`` forbids even that scalar read for DEVICE values
    (host numpy stays readable — it costs nothing): the async fetch
    path (``Executor.run(fetch_async=True)`` / lazy Tensors) must not
    pay a hidden per-step device->host block just for logging."""
    import numpy as np

    v = getattr(v, "_data", v)
    shape, dtype = getattr(v, "shape", None), getattr(v, "dtype", None)
    if shape is None or dtype is None:
        if isinstance(v, (bool, int, float)):
            return float(v)
        return {"repr": repr(v)[:80]}
    size = 1
    for s in shape:
        size *= int(s)
    readable = sync or isinstance(v, (np.ndarray, np.generic))
    try:
        if size == 1 and readable and np.dtype(dtype).kind in "fiub":
            return float(np.asarray(v).reshape(()))
    except (TypeError, ValueError):
        pass
    return {"shape": [int(s) for s in shape], "dtype": str(dtype)}


class RunJournal:
    """One run's flight recorder. Usable three ways:

    - process-wide via env: ``PADDLE_TPU_RUN_DIR=/runs/exp7`` auto-starts
      a journal at import and every instrumented site feeds it;
    - explicitly: ``j = obs.start_run("/runs/exp7")`` ... ``obs.end_run()``;
    - scoped: ``with RunJournal("/runs/exp7") as j:`` — an exception
      leaving the block writes the postmortem before closing.

    Rank identity (multi-process gangs): with ``rank=`` (or env
    ``PADDLE_TPU_RANK``, which GangSupervisor / ``dist.launch`` set per
    worker) the journal writes under ``<run_dir>/rank_NN/`` — each rank
    owns its file, so N workers journaling into one run dir can never
    tear each other's lines. ``obs.fleet`` aggregates the rank subdirs
    back into one cross-rank view.
    """

    def __init__(self, run_dir=None, *, rank=None, flush_every=32,
                 flush_interval_s=5.0, max_bytes=64 << 20,
                 postmortem_steps=64, detectors=None,
                 anomaly_callback=None, peak=None, compute_flops=None):
        run_dir = run_dir or os.environ.get("PADDLE_TPU_RUN_DIR")
        if not run_dir:
            raise ValueError(
                "RunJournal needs a run directory: pass run_dir or set "
                "PADDLE_TPU_RUN_DIR")
        self.rank = env_rank() if rank is None else int(rank)
        if self.rank is not None and os.path.basename(
                os.path.normpath(str(run_dir))) != rank_subdir(self.rank):
            # a launcher that already handed us our per-rank subdir
            # (basename matches) must not get a second nesting level
            run_dir = os.path.join(str(run_dir), rank_subdir(self.rank))
        self.run_dir = str(run_dir)
        self.flush_every = max(1, int(flush_every))
        self.flush_interval_s = float(flush_interval_s)
        self.max_bytes = int(max_bytes)
        if compute_flops is None:
            # default on, env-defeatable: the lazy per-entry FLOPs
            # attribution pays a BACKGROUND analysis compile per entry —
            # free wall-clock normally, but real CPU contention inside a
            # worker racing a heartbeat watchdog on a loaded host
            # (PADDLE_TPU_JOURNAL_FLOPS=0 is how gang drills quiet it)
            compute_flops = os.environ.get(
                "PADDLE_TPU_JOURNAL_FLOPS", "").lower() not in \
                ("0", "false", "off")
        self.compute_flops = bool(compute_flops)
        # leaf lock: record/event paths are called from under the
        # scheduler/engine/prefetcher locks, so nothing may be
        # acquired while THIS is held (lockdep enforces it)
        self._lock = _lockdep.rlock("obs.journal")
        self._buf = []
        self._file = None
        self._bytes = 0
        self._part = 0
        self._last_flush = time.monotonic()
        self._closed = True
        self._step = 0
        self._t_start = None
        self._last_timer_ms = None
        self._last_steps = deque(maxlen=int(postmortem_steps))
        self._last_events = deque(maxlen=int(postmortem_steps))
        self._postmortem_written = False
        self._backend_written = False
        self.accounting = MFUAccounting(peak=peak)
        if detectors is None:
            try:
                detectors = default_detectors()
            except Exception as e:
                # a typo'd PADDLE_TPU_ANOMALY spec must cost the
                # detectors, not the whole flight recorder
                import warnings

                warnings.warn(
                    f"anomaly detectors disabled — bad PADDLE_TPU_ANOMALY "
                    f"spec? ({type(e).__name__}: {e})", RuntimeWarning)
                detectors = []
        self.anomalies = AnomalyEngine(detectors,
                                       callback=anomaly_callback)
        # metrics baselines for per-step deltas (interned refs stay live
        # across obs.metrics.reset())
        self._m_hits = _metrics.counter("executor.jit_cache.hits")
        self._m_misses = _metrics.counter("executor.jit_cache.misses")
        self._m_queue = _metrics.gauge("dataloader.queue_depth")
        self._m_wait = _metrics.histogram("dataloader.consumer_wait_ms")
        self._hits0 = self._mis0 = 0
        self._wait0 = 0.0

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        with self._lock:
            if not self._closed:
                return self
            os.makedirs(self.run_dir, exist_ok=True)
            self._file = open(self._path(), "a", encoding="utf-8")
            self._bytes = self._file.tell()
            # resume-safe rotation: continue numbering after any parts a
            # previous run into this dir already rotated out, or
            # os.replace would silently clobber journal.1.jsonl
            for fn in os.listdir(self.run_dir):
                if fn.startswith("journal.") and fn.endswith(".jsonl") \
                        and fn != JOURNAL_FILE:
                    try:
                        self._part = max(self._part,
                                         int(fn.split(".")[1]))
                    except ValueError:
                        pass
            self._closed = False
            self._t_start = time.monotonic()
            self._hits0 = self._m_hits.value
            self._mis0 = self._m_misses.value
            self._wait0 = self._m_wait.sum
            atexit.register(self._atexit)
        # NOTE: no backend info / peak-FLOPs probe here — start() runs at
        # import when PADDLE_TPU_RUN_DIR is set, and touching
        # jax.devices() would pin the platform before the user's own
        # config (or block on a dead tunnel). A "backend" event is
        # emitted lazily with the first step record instead.
        rec = {
            "t": "run_start", "ts": time.time(), "pid": os.getpid(),
            "argv": list(sys.argv), "run_dir": self.run_dir,
            "env": _env_knobs()}
        if self.rank is not None:
            rec["rank"] = self.rank
        self._write(rec)
        return self

    def close(self, exc=None):
        """Write the run_end summary and release the file. ``exc`` (an
        exception instance) additionally writes the postmortem first."""
        with self._lock:
            if self._closed:
                return
            if exc is not None:
                self.postmortem(exc)
            elif _trace.tracing_enabled() and not self._postmortem_written:
                # clean close with tracing on: leave the per-run Chrome
                # trace next to the journal (per-rank exports are what
                # obs.fleet.merge_chrome_traces fuses into fleet lanes)
                try:
                    _trace.export_chrome_trace(
                        os.path.join(self.run_dir, TRACE_FILE))
                except Exception:
                    pass
            self._write({"t": "run_end", "ts": time.time(),
                         "summary": self.summary()}, _locked=True)
            self._flush_locked()
            self._file.close()
            self._file = None
            self._closed = True
            try:
                atexit.unregister(self._atexit)
            except Exception:
                pass
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = None

    def _atexit(self):
        try:
            self.close()
        except Exception:
            pass

    def _adopt_trace_rank(self):
        """Becoming the PROCESS-WIDE journal with a rank identity also
        adopts that rank for trace exports (one process = one rank), so
        per-rank Chrome traces fuse collision-free. A standalone
        (non-installed) journal never mutates global trace state —
        test fixtures build many ranks in one process."""
        if self.rank is not None and _trace.current_rank() is None:
            _trace.set_rank(self.rank)

    def __enter__(self):
        """Scoped use installs the journal process-wide for the block —
        the hooks all read ``journal.ACTIVE``, so a non-installed
        journal would record nothing."""
        global ACTIVE
        self._prev_active = ACTIVE
        self.start()
        ACTIVE = self
        self._adopt_trace_rank()
        return self

    def __exit__(self, exc_type, exc, tb):
        global ACTIVE
        self.close(exc=exc)
        prev = getattr(self, "_prev_active", None)
        if ACTIVE is None and prev is not None and not prev.closed:
            ACTIVE = prev
        return False

    @property
    def closed(self):
        return self._closed

    # -- write path ----------------------------------------------------------
    def _path(self):
        return os.path.join(self.run_dir, JOURNAL_FILE)

    def _write(self, rec, _locked=False):
        line = json.dumps(rec, default=str)
        lock = self._lock
        if _locked:
            self._buf.append(line)
            self._maybe_flush_locked(len(line))
            return
        with lock:
            if self._closed:
                return
            self._buf.append(line)
            self._maybe_flush_locked(len(line))

    def _maybe_flush_locked(self, nbytes):
        self._bytes += nbytes + 1
        now = time.monotonic()
        if len(self._buf) >= self.flush_every or \
                now - self._last_flush >= self.flush_interval_s:
            self._flush_locked()

    def _flush_locked(self):
        if self._buf and self._file is not None:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
            self._buf.clear()
        self._last_flush = time.monotonic()
        if self._bytes >= self.max_bytes and self._file is not None:
            self._file.close()
            self._part += 1
            os.replace(self._path(), os.path.join(
                self.run_dir, f"journal.{self._part}.jsonl"))
            self._file = open(self._path(), "a", encoding="utf-8")
            self._bytes = 0

    def flush(self):
        with self._lock:
            if not self._closed:
                self._flush_locked()

    # -- recording -----------------------------------------------------------
    def record_step(self, loss=None, fetches=None, step_ms=None,
                    examples=None, flops=None, skipped=False,
                    nonfinite=False, source=None, comm=None, **extra):
        """Append one per-step record. ``loss`` must already be a host
        scalar (or None); ``fetches`` a list of host-side values."""
        import math

        # host-side value summarization stays OUTSIDE the lock (it may
        # read a scalar off-device); all shared mutation — step counter,
        # metric baselines, accounting, detectors, buffers — happens
        # under ONE lock hold so concurrent steppers can't lose counts
        # or mutate a detector window mid-iteration
        if loss is not None:
            try:
                loss = float(loss)
            except (TypeError, ValueError):
                loss = None
        if loss is not None and not math.isfinite(loss):
            nonfinite = True
        fetch_summary = extra.pop("_fetch_summary", None)
        if fetch_summary is None and fetches:
            fetch_summary = [_summarize_value(v) for v in fetches[:4]]
        with self._lock:
            if self._closed:
                return None
            if not self._backend_written:
                # deferred from start(): by the first recorded step a
                # real run has initialized its backend, so this probe is
                # a metadata read, never a backend-creating side effect
                self._backend_written = True
                self.event("backend", peak_flops_per_s=peak_flops(),
                           **_backend_info())
            self._step += 1
            step = self._step
            hits, misses = self._m_hits.value, self._m_misses.value
            dhits, dmis = hits - self._hits0, misses - self._mis0
            self._hits0, self._mis0 = hits, misses
            wait = self._m_wait.sum
            dwait, self._wait0 = wait - self._wait0, wait
            if step_ms is None:
                step_ms, self._last_timer_ms = self._last_timer_ms, None
            rec = {"t": "step", "step": step, "ts": time.time(),
                   "loss": loss, "step_ms": step_ms}
            if fetch_summary:
                rec["fetches"] = fetch_summary
            if examples:
                rec["examples"] = int(examples)
                if step_ms:
                    rec["examples_per_s"] = examples / (step_ms / 1e3)
            if flops:
                rec["flops"] = float(flops)
            if dhits or dmis:
                rec["jit_cache"] = {"hits": dhits, "misses": dmis}
            qd = self._m_queue.value
            if qd:
                rec["queue_depth"] = qd
            if dwait > 0:
                rec["dl_wait_ms"] = dwait
            if comm:
                rec["comm"] = comm
            if skipped:
                rec["skipped"] = True
            if nonfinite:
                rec["nonfinite"] = True
            if source:
                rec["source"] = source
            rec.update(extra)
            self.accounting.record(
                step_ms=step_ms, flops=flops, examples=examples,
                productive=not (skipped or nonfinite),
                comm_bytes=(comm or {}).get("total_bytes"),
                wire_bytes=(comm or {}).get("wire_bytes"),
                # a fused window is ONE record but K optimizer steps:
                # goodput / productive-step counts weight by it
                weight=rec.get("steps_fused") or 1)
            self._last_steps.append(rec)
            self._write(rec, _locked=True)
            for fired in self.anomalies.observe(rec):
                self._write({"t": "anomaly", "ts": time.time(), **fired},
                            _locked=True)
        return rec

    def record_request(self, rid, state=None, arrival_t=None,
                       admit_t=None, first_token_t=None, finish_t=None,
                       prompt_tokens=None, output_tokens=None,
                       pages_peak=None, preemptions=0, **extra):
        """Append one per-request serving record (the decode analog of
        a training step record): the request's lifecycle timestamps in
        the SERVING clock (the engine's injectable clock, so tests are
        exact), derived TTFT/TPOT/e2e/queue latencies in ms, and the
        KV-page + preemption footprint. Per-phase ms fields ride
        ``extra`` (the engine passes ``prefill_ms``/``preempt_ms``/
        ``decode_ms`` from its preempt/resume stamps; with the derived
        ``queue_ms`` they telescope exactly to ``e2e_ms`` — the
        ``obs.reqtrace`` attribution invariant).
        ``tools/run_report.py`` summarizes these into p50/p99
        columns."""
        rec = {"t": "request", "rid": rid, "ts": time.time()}
        if state is not None:
            rec["state"] = state
        for k, v in (("arrival_t", arrival_t), ("admit_t", admit_t),
                     ("first_token_t", first_token_t),
                     ("finish_t", finish_t)):
            if v is not None:
                rec[k] = float(v)
        if prompt_tokens is not None:
            rec["prompt_tokens"] = int(prompt_tokens)
        if output_tokens is not None:
            rec["output_tokens"] = int(output_tokens)
        if pages_peak is not None:
            rec["pages_peak"] = int(pages_peak)
        if preemptions:
            rec["preemptions"] = int(preemptions)
        if arrival_t is not None and admit_t is not None:
            rec["queue_ms"] = (admit_t - arrival_t) * 1e3
        if arrival_t is not None and first_token_t is not None:
            rec["ttft_ms"] = (first_token_t - arrival_t) * 1e3
        if arrival_t is not None and finish_t is not None:
            rec["e2e_ms"] = (finish_t - arrival_t) * 1e3
        if first_token_t is not None and finish_t is not None and \
                output_tokens and output_tokens > 1:
            rec["tpot_ms"] = (finish_t - first_token_t) * 1e3 / \
                (output_tokens - 1)
        rec.update(extra)
        with self._lock:
            if self._closed:
                return None
            self._write(rec, _locked=True)
        return rec

    def event(self, kind, **fields):
        """Append one discrete event record (compile, checkpoint,
        resilience recovery, chaos activation, ...)."""
        with self._lock:
            if self._closed:
                return None
            if kind.startswith("resilience.retry"):
                self.accounting.note_retry()
            elif kind in ("resilience.skipped", "resilience.rollbacks") \
                    and fields.get("source") == "guarded_executor":
                # ONLY the static guard discards a step AFTER the
                # executor hook recorded it as productive: reclassify
                # that record. The eager GuardedStep records its own
                # skipped steps (its event says source="guarded_step"),
                # and without the source check it would misreclassify an
                # unrelated earlier executor step (e.g. an eval pass).
                # The step's JSONL line is already flushed, so the
                # correction is carried ON THIS EVENT
                # (reclassified_step) — readers (tools/run_report.py)
                # apply it when loading.
                last = self._last_steps[-1] if self._last_steps else None
                if last is not None and last.get("source") == "executor" \
                        and not (last.get("skipped")
                                 or last.get("nonfinite")):
                    last["skipped"] = True
                    self.accounting.reclassify_skip()
                    fields = dict(fields,
                                  reclassified_step=last["step"])
            rec = {"t": "event", "kind": kind, "ts": time.time(),
                   "step": self._step, **fields}
            self._last_events.append(rec)
            self._write(rec, _locked=True)
        return rec

    def record_plan(self, plan, **fields):
        """One ``plan`` event per auto-parallel compile
        (``fleet.auto_parallel`` / ``auto_parallel_step``): the mesh
        shape, per-axis roles, canonical axes, and the planner's
        predicted vs HLO-measured collective wire bytes (mismatch is
        their relative delta; None until ``fleet.verify_plan`` ran).
        One payload shape for both the static and eager paths —
        ``tools/run_report.py`` renders it and gates on the mismatch
        in ``--diff``."""
        return self.event("plan", **plan.event_fields(), **fields)

    def record_memory(self, compiled=None, analysis=None,
                      predicted_bytes=None, per_device_bytes=None,
                      measured_bytes=None, **fields):
        """One ``memory`` event per compiled entry: the static
        peak-HBM prediction (``analysis.memory.estimate_entry``,
        attached by ``Executor._build``) and — once the entry's lazy
        analysis has landed — the executable's own
        ``memory_analysis()`` total, with ``drift`` their relative
        delta. Emitted twice per entry like ``plan`` events: once at
        compile (predicted only) and once measured; readers
        (``tools/run_report.py``) take the measured record. ``drift``
        compares the per-device prediction on mesh entries (XLA
        reports per-device allocations) and the total otherwise.
        Synthetic callers (self-tests) pass the byte fields
        directly."""
        sharded = False
        if compiled is not None:
            pm = getattr(compiled, "predicted_memory", None) or {}
            if predicted_bytes is None:
                predicted_bytes = pm.get("peak_bytes")
            if per_device_bytes is None:
                per_device_bytes = pm.get("per_device_bytes")
            sharded = bool(getattr(compiled, "mesh_axes", None))
            fields.setdefault("entry_uid",
                              getattr(compiled, "program_uid", None))
            fields.setdefault("version",
                              getattr(compiled, "program_version", None))
            if getattr(compiled, "steps", None):
                fields.setdefault("steps_fused", compiled.steps)
            if analysis is not None and measured_bytes is None:
                mem = analysis.get("memory") or None
                if mem:
                    from ..analysis.memory import measured_peak_bytes

                    measured_bytes = measured_peak_bytes(mem)
        drift = None
        ref = per_device_bytes if (sharded and per_device_bytes) \
            else predicted_bytes
        if ref and measured_bytes:
            drift = abs(ref - measured_bytes) / measured_bytes
        return self.event(
            "memory", predicted_peak_bytes=predicted_bytes,
            per_device_bytes=per_device_bytes,
            measured_peak_bytes=measured_bytes, drift=drift, **fields)

    def note_step_ms(self, ms):
        """StepTimer feed: remember the latest timed step so the next
        ``record_step`` without an explicit ``step_ms`` uses it."""
        self._last_timer_ms = float(ms)

    def sync_step(self, global_step):
        """Align the journal's step numbering with the trainer's OWN
        global step: the next recorded step gets number
        ``global_step``. Elastic workers call this once per loop
        iteration so a relaunched incarnation's records continue at
        its resume step instead of restarting at 1 — which is what
        lets ``obs.fleet.align_steps`` line records up across ranks
        AND attempts by global step."""
        with self._lock:
            self._step = int(global_step) - 1

    def _entry_flops_comm(self, compiled):
        """Non-blocking per-entry FLOPs + collective attribution (a
        background thread pays the analysis compile; early steps carry
        None)."""
        flops = comm = None
        if self.compute_flops:
            from .mfu import entry_analysis_nowait

            analysis = entry_analysis_nowait(compiled)
            if analysis is not None:
                if not getattr(compiled, "_memory_journaled", False):
                    # the measured half of the per-entry memory event:
                    # memory_analysis() landed with the lazy analysis,
                    # so journal predicted-vs-measured ONCE per entry
                    compiled._memory_journaled = True
                    try:
                        self.record_memory(compiled, analysis=analysis)
                    except Exception:
                        pass
                flops = float((analysis["cost"] or {}).get("flops")
                              or 0) or None
                prof = analysis.get("collectives")
                if prof and prof.get("n_ops"):
                    # the entry's per-execution collective volume IS the
                    # step's comm delta (one executable run per step)
                    comm = {
                        "total_bytes": prof["total_bytes"],
                        "wire_bytes": prof["wire_bytes"],
                        "quant_wire_bytes":
                            prof.get("quant_wire_bytes", 0),
                        "all_reduce_bytes":
                            prof["bytes"].get("all-reduce", 0),
                        "n_ops": prof["n_ops"],
                    }
        return flops, comm

    # called from the Executor run hook: everything here is host-side
    # metadata — the FLOPs/comm lookup is non-blocking (a background
    # thread pays the entry's analysis compile; early steps carry
    # flops=None and no comm attribution). ``synced=False`` (lazy /
    # async fetches) keeps even the size-1 loss summary off the device.
    def record_executor_run(self, compiled, fetches, run_ms, synced=True,
                            source="executor", examples=None):
        flops, comm = self._entry_flops_comm(compiled)
        # summarize ONCE and reuse: with lazy fetches
        # (return_numpy=False) each size-1 summary is a scalar device
        # read, and doing it twice would double the step's logging sync
        summary = [_summarize_value(v, sync=synced)
                   for v in fetches[:4]] if fetches else None
        loss = summary[0] if summary and isinstance(summary[0], float) \
            else None
        if examples is None:
            # entry-shape fallback; a batch-bucketed caller (the
            # Predictor pads to its bucket) passes the TRUE count so
            # examples/s never counts padding
            examples = getattr(compiled, "examples_hint", None)
        return self.record_step(
            loss=loss, step_ms=run_ms, examples=examples,
            flops=flops, comm=comm, source=source,
            _fetch_summary=summary)

    def record_fused_run(self, compiled, fetches, run_ms, steps,
                         synced=True):
        """One fused ``Executor.run_steps`` dispatch = ONE step record
        carrying ``steps_fused=K`` (not K records: the flight recorder
        mirrors dispatches, and fan-out would fabricate K identical
        timings from one measurement). ``loss`` is the LAST microbatch's
        (the trajectory endpoint the anomaly detectors should track);
        ``examples`` covers all K microbatches, and the entry's FLOPs /
        collective volumes already describe the whole K-step executable,
        so MFU and comm accounting stay exact."""
        import numpy as np

        steps = int(steps)
        flops, comm = self._entry_flops_comm(compiled)
        summary = [_summarize_value(v, sync=synced)
                   for v in fetches[:4]] if fetches else None
        loss = None
        if fetches and synced:
            try:  # stacked (K,) trajectory -> endpoint scalar
                arr = np.asarray(getattr(fetches[0], "_data", fetches[0]))
                if arr.shape == (steps,) and arr.dtype.kind in "fiub":
                    loss = float(arr[-1])
            except (TypeError, ValueError):
                pass
        hint = getattr(compiled, "examples_hint", None)
        return self.record_step(
            loss=loss, step_ms=run_ms,
            examples=hint * steps if hint else None,
            flops=flops, comm=comm, source="executor",
            steps_fused=steps, _fetch_summary=summary)

    # -- summaries -----------------------------------------------------------
    def summary(self):
        out = self.accounting.summary()
        out["steps"] = self._step  # records (= dispatches), unchanged
        # optimizer steps weight fused windows by K (steps_fused): the
        # number a sequential run of the same training is comparable to
        opt_steps = self.accounting.productive + self.accounting.skipped
        out["optimizer_steps"] = opt_steps
        if self._t_start is not None:
            wall = time.monotonic() - self._t_start
            out["wall_s"] = wall
            if wall > 0 and self._step:
                out["steps_per_s"] = self._step / wall
            if wall > 0 and opt_steps:
                out["optimizer_steps_per_s"] = opt_steps / wall
        out["anomalies_fired"] = len(self.anomalies.fired)
        return out

    def postmortem(self, exc=None, note=None):
        """Dump ``postmortem.json``: run header context, the last-K step
        records and events, the exception (if any), a metrics snapshot,
        and — when span tracing is on — a Chrome trace next to it."""
        with self._lock:
            dump = {
                "ts": time.time(), "run_dir": self.run_dir,
                "note": note, "summary": self.summary(),
                "last_steps": list(self._last_steps),
                "last_events": list(self._last_events),
                "anomalies": list(self.anomalies.fired),
                "metrics": _metrics.snapshot(),
            }
            if exc is not None:
                import traceback

                dump["exception"] = {
                    "type": type(exc).__name__, "message": str(exc),
                    "traceback": traceback.format_exception(
                        type(exc), exc, exc.__traceback__),
                }
            path = os.path.join(self.run_dir, POSTMORTEM_FILE)
            os.makedirs(self.run_dir, exist_ok=True)
            if _trace.tracing_enabled():
                # export BEFORE the dump is serialized, so the
                # postmortem actually carries the trace pointer
                try:
                    trace_path = os.path.join(self.run_dir, TRACE_FILE)
                    _trace.export_chrome_trace(trace_path)
                    dump["trace_file"] = trace_path
                except Exception:
                    pass
            with open(path, "w", encoding="utf-8") as f:
                json.dump(dump, f, default=str, indent=1)
            self._postmortem_written = True
            if not self._closed:
                self.event("postmortem", path=path,
                           error=(f"{type(exc).__name__}: {exc}"
                                  if exc is not None else note))
                self._flush_locked()
        return path


def start_run(run_dir=None, **kw):
    """Create, start, and install the process-wide journal (replacing
    any previous one after closing it). ``run_dir`` defaults to env
    ``PADDLE_TPU_RUN_DIR``."""
    global ACTIVE
    if ACTIVE is not None:
        ACTIVE.close()
    j = RunJournal(run_dir, **kw).start()
    ACTIVE = j
    j._adopt_trace_rank()
    return j


def end_run(exc=None):
    """Close and uninstall the process-wide journal (no-op without
    one). Returns the final summary dict, or None."""
    global ACTIVE
    j, ACTIVE = ACTIVE, None
    if j is None:
        return None
    out = j.summary()
    j.close(exc=exc)
    return out


if os.environ.get("PADDLE_TPU_RUN_DIR"):
    try:
        start_run()
    except Exception as _e:  # an unwritable dir must not poison import —
        ACTIVE = None        # but a silently-missing flight record is a
        import warnings      # debugging trap, so say it happened

        warnings.warn(
            f"PADDLE_TPU_RUN_DIR is set but the run journal failed to "
            f"start ({type(_e).__name__}: {_e}); no flight record will "
            "be written", RuntimeWarning)
