"""Cross-rank fleet observability: aggregate per-rank run journals.

The flight recorder (``obs.journal``) is per-process; an elastic gang
or a serve fleet is N processes, each journaling into its own
``<run_dir>/rank_NN/`` subdir (the supervisor's own events land in
``<run_dir>/supervisor/``). This module is the read side that turns
those N single-rank records back into ONE run:

- :func:`load_journal` — the canonical journal parser (header, steps,
  events, requests, anomalies, summary; torn-tail tolerant; steps
  annotated with their incarnation so elastic re-executions stay
  attributable). ``tools/run_report.py`` delegates to it.
- :func:`align_steps` / :func:`step_skew` — align step records across
  ranks by GLOBAL step and compute per-step max/median step time,
  slowest-rank attribution, and the slowest rank's ratio to the median
  of the others (the per-worker step-time skew the MLPerf TPU-pod
  scaling playbook, arXiv 1909.09756, treats as the first-order
  scaling diagnostic).
- :class:`StragglerDetector` — persistent-straggler detection in the
  ``obs.anomaly`` re-arm style: fires once per episode, a recovery
  re-arms it.
- :func:`stall_attribution` — hung-rank attribution for attempts the
  supervisor ended in a hang, from the JOURNALS (the rank whose record
  stream stops earliest), because the watchdog's kill rank is
  poll-granularity noisy: a gang stalled on a collective (or a barrier)
  goes heartbeat-quiet together.
- :func:`aggregate` — the fleet rollup: per-rank table, skew summary,
  stragglers, gang goodput/MFU/throughput totals, merged request
  percentiles across serve replicas, supervisor elasticity columns.
- :func:`merge_chrome_traces` — fuse per-rank Chrome traces into one
  Perfetto file with pid=rank lanes (device counter lanes are
  rank-namespaced inside the ``DEVICE_PID_BASE`` band so two ranks'
  device 0 never share a pid).

``tools/fleet_report.py`` is the CLI front door; ``obs.export`` serves
the live-signal complement (Prometheus SLO gauges).
"""
from __future__ import annotations

import json
import os
import re
import time

from .journal import (JOURNAL_FILE, ROUTER_DIR,  # noqa: F401
                      SUPERVISOR_DIR, TRACE_FILE, rank_subdir)
from .trace import DEVICE_PID_BASE, RANK_PID_STRIDE

__all__ = [
    "SUPERVISOR_DIR", "ROUTER_DIR", "SUPERVISOR_PID", "rank_dirs",
    "supervisor_dirs", "router_dir", "journal_files",
    "load_journal", "load_fleet", "align_steps", "step_skew",
    "StragglerDetector", "detect_stragglers", "stall_attribution",
    "request_summary", "merged_request_summary", "elastic_summary",
    "router_summary", "slo_summary", "tenant_summary",
    "merged_tenant_summary", "per_rank_summary", "aggregate",
    "heartbeat_ages", "merge_chrome_traces", "rank_subdir",
]

# the supervisor's merged-trace lane: above any plausible rank, below
# the device pid band
SUPERVISOR_PID = 1 << 16

_RANK_DIR_RE = re.compile(r"^rank_(\d+)$")


def _median(values):
    s = sorted(values)
    n = len(s)
    if not n:
        return None
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _pctl(xs, q):
    from .metrics import exact_percentile

    return exact_percentile(xs, q)


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# -- loading -----------------------------------------------------------------


def journal_files(path):
    """The journal file(s) for one run: a file path as-is; a directory
    yields rotated parts (``journal.<n>.jsonl``, oldest first) then the
    live ``journal.jsonl`` tail."""
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        return []
    parts = []
    for fn in os.listdir(path):
        if fn.startswith("journal.") and fn.endswith(".jsonl") \
                and fn != JOURNAL_FILE:
            try:
                parts.append((int(fn.split(".")[1]), fn))
            except ValueError:
                pass
    out = [os.path.join(path, fn) for _, fn in sorted(parts)]
    live = os.path.join(path, JOURNAL_FILE)
    if os.path.exists(live):
        out.append(live)
    return out


def load_journal(path):
    """Parse one rank's (or process's) journal into ``{header, steps,
    events, anomalies, requests, run_starts, summary, parse_errors}``.
    Tolerates a torn final line (a crashed writer) — it lands in
    ``parse_errors``, everything before it loads.

    An elastic worker appends a fresh ``run_start`` per incarnation
    into the SAME per-rank dir; each step record is annotated with its
    1-based ``_incarnation`` ordinal (``run_starts[k-1]`` is that
    incarnation's header) so re-executed steps stay attributable to
    the attempt that ran them. ``header`` is the LAST incarnation's.
    """
    files = journal_files(path)
    if not files:
        raise FileNotFoundError(f"no {JOURNAL_FILE} under {path!r}")
    run = {"header": None, "steps": [], "events": [], "anomalies": [],
           "requests": [], "run_starts": [], "summary": None,
           "parse_errors": []}
    for fp in files:
        with open(fp, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    run["parse_errors"].append(
                        f"{os.path.basename(fp)}:{lineno}: {e}")
                    continue
                t = rec.get("t")
                if t == "run_start":
                    run["header"] = rec
                    run["run_starts"].append(rec)
                elif t == "step":
                    rec["_incarnation"] = len(run["run_starts"])
                    run["steps"].append(rec)
                elif t == "anomaly":
                    run["anomalies"].append(rec)
                elif t == "run_end":
                    run["summary"] = rec.get("summary")
                elif t == "event":
                    rec["_incarnation"] = len(run["run_starts"])
                    run["events"].append(rec)
                elif t == "request":
                    run["requests"].append(rec)
    # keyed by (incarnation, step): an elastic resume re-executes step
    # numbers into the SAME file, and a correction event from one
    # incarnation must never flag a later incarnation's clean re-run
    by_step = {(s["_incarnation"], s.get("step")): s
               for s in run["steps"]}
    for e in run["events"]:
        if e.get("kind") == "backend" and run["header"] is not None:
            # backend identity is journaled lazily (first step) so the
            # run header never forces backend init; fold it back in
            for k in ("backend", "ndev", "device_kind",
                      "peak_flops_per_s"):
                if k in e:
                    run["header"].setdefault(k, e[k])
        step = e.get("reclassified_step")
        key = (e.get("_incarnation"), step)
        if step is not None and key in by_step:
            # the step's line was already durable when the guard
            # discarded it; the correction rides the event
            by_step[key]["skipped"] = True
    return run


def rank_dirs(run_dir):
    """``{rank: path}`` for every ``rank_NN`` subdir of ``run_dir``
    holding a journal. Empty when ``run_dir`` is single-process."""
    out = {}
    if not run_dir or not os.path.isdir(run_dir):
        return out
    for fn in os.listdir(run_dir):
        m = _RANK_DIR_RE.match(fn)
        if not m:
            continue
        p = os.path.join(run_dir, fn)
        if os.path.isfile(os.path.join(p, JOURNAL_FILE)):
            out[int(m.group(1))] = p
    return out


def supervisor_dirs(run_dir):
    """``{rank_base: path}`` for every supervisor journal under
    ``run_dir``: the single-node ``supervisor/`` is base 0; a
    multi-node launch adds one ``supervisor_NN/`` per non-zero node
    (NN = that node's first global rank — GangSupervisor's
    ``rank_base``)."""
    out = {}
    if not run_dir or not os.path.isdir(run_dir):
        return out
    for fn in os.listdir(run_dir):
        if fn == SUPERVISOR_DIR:
            base = 0
        elif fn.startswith(SUPERVISOR_DIR + "_"):
            try:
                base = int(fn[len(SUPERVISOR_DIR) + 1:])
            except ValueError:
                continue
        else:
            continue
        p = os.path.join(run_dir, fn)
        if os.path.isfile(os.path.join(p, JOURNAL_FILE)):
            out[base] = p
    return out


def router_dir(run_dir):
    """The serve-fleet router's journal dir under ``run_dir``
    (``router/``, written by ``serving.fleet.Router``'s host process),
    or None."""
    if not run_dir:
        return None
    p = os.path.join(str(run_dir), ROUTER_DIR)
    if os.path.isfile(os.path.join(p, JOURNAL_FILE)):
        return p
    return None


def load_fleet(run_dir):
    """Load every rank journal (+ every supervisor's and the serve
    router's, when present) under ``run_dir`` into ``{run_dir, ranks:
    {rank: run}, supervisors: {rank_base: run}, supervisor, router}``;
    ``supervisor`` stays the base-0 record for single-node callers."""
    ranks = rank_dirs(run_dir)
    if not ranks:
        raise FileNotFoundError(
            f"no rank_NN journals under {run_dir!r} — not a fleet run "
            "dir (single-process runs render via tools/run_report.py)")
    fleet = {"run_dir": str(run_dir),
             "ranks": {r: load_journal(p)
                       for r, p in sorted(ranks.items())},
             "supervisors": {}, "supervisor": None, "router": None}
    for base, p in sorted(supervisor_dirs(run_dir).items()):
        fleet["supervisors"][base] = load_journal(p)
    fleet["supervisor"] = fleet["supervisors"].get(0)
    rd = router_dir(run_dir)
    if rd:
        fleet["router"] = load_journal(rd)
    return fleet


# -- cross-rank alignment + skew ---------------------------------------------


def align_steps(fleet):
    """``[{step, by_rank: {rank: step-record}}]`` sorted by GLOBAL
    step. A step re-executed after an elastic resume keeps the LAST
    record per rank — the execution the final trajectory used."""
    by_step = {}
    for rank, run in fleet["ranks"].items():
        for rec in run["steps"]:
            s = rec.get("step")
            if isinstance(s, int):
                by_step.setdefault(s, {})[rank] = rec
    return [{"step": s, "by_rank": by_step[s]} for s in sorted(by_step)]


def step_skew(aligned):
    """Per aligned step with >= 2 ranks reporting a positive
    ``step_ms``: ``skew`` = max/median across ranks, the slowest rank
    (lowest rank wins a tie, deterministically), and
    ``slowest_vs_others`` = slowest over the median of the OTHER ranks
    — the per-rank straggler magnitude (2.0 reads "this rank ran the
    step at half the speed of the rest of the gang")."""
    rows = []
    for row in aligned:
        ms = {r: rec["step_ms"] for r, rec in row["by_rank"].items()
              if _num(rec.get("step_ms")) and rec["step_ms"] > 0}
        if len(ms) < 2:
            continue
        med = _median(ms.values())
        slowest = max(sorted(ms), key=lambda r: ms[r])
        others_med = _median([v for r, v in ms.items() if r != slowest])
        rows.append({
            "step": row["step"], "nranks": len(ms),
            "max_ms": ms[slowest], "median_ms": med,
            "skew": (ms[slowest] / med) if med else None,
            "slowest": slowest,
            "slowest_vs_others": (ms[slowest] / others_med)
            if others_med else None,
        })
    return rows


class StragglerDetector:
    """Persistent-straggler detection in the ``obs.anomaly`` re-arm
    style over :func:`step_skew` rows: fires ONCE per episode when the
    SAME rank is slowest for ``patience`` consecutive compared steps at
    >= ``factor`` x the median of the other ranks; a recovery (the
    ratio dropping under ``factor``, or the slowest rank changing)
    resets the streak and re-arms the detector for the next episode."""

    name = "persistent_straggler"

    def __init__(self, factor=1.5, patience=3):
        self.factor = float(factor)
        self.patience = max(1, int(patience))
        self._rank = None
        self._streak = 0
        self._first = None

    def update(self, row):
        ratio = row.get("slowest_vs_others")
        if ratio is None or ratio < self.factor:
            self._rank, self._streak, self._first = None, 0, None
            return None
        if row["slowest"] != self._rank:
            self._rank = row["slowest"]
            self._streak = 0
            self._first = row["step"]
        self._streak += 1
        if self._streak == self.patience:  # once per episode
            return {"rank": self._rank, "first_step": self._first,
                    "step": row["step"], "ratio": ratio,
                    "streak": self._streak}
        return None


def detect_stragglers(rows, factor=1.5, patience=3):
    """Every persistent-straggler episode in the skew rows, tagged
    ``kind="slow"``."""
    det = StragglerDetector(factor=factor, patience=patience)
    out = []
    for row in rows:
        fired = det.update(row)
        if fired:
            out.append(dict(fired, kind="slow"))
    return out


def _attempt_of(run, incarnation):
    """The supervisor attempt index a step's incarnation ran under
    (``PADDLE_TPU_ELASTIC_ATTEMPT`` from that incarnation's run_start
    env; ordinal fallback for unsupervised runs)."""
    if not incarnation or incarnation > len(run["run_starts"]):
        return None
    env = run["run_starts"][incarnation - 1].get("env") or {}
    try:
        return int(env.get("PADDLE_TPU_ELASTIC_ATTEMPT"))
    except (TypeError, ValueError):
        return incarnation - 1


def stall_attribution(fleet):
    """Hung-rank attribution, tagged ``kind="hang"``: for each attempt
    the supervisor restarted on a hang — and for a terminal hang that
    exhausted the restart budget — the rank whose journal stops at
    the LOWEST step in that attempt is the one that stopped making
    progress. The supervisor's ``elastic.watchdog_kill`` rank is NOT
    trusted for this: a rank hung at a barrier (or collective) stalls
    every other rank's heartbeat within one step, and the watchdog
    reports whichever stale beacon it polled first. ``ambiguous`` is
    set when the journals cannot separate the ranks (all stopped at the
    same step)."""
    sups = _supervisors(fleet)
    if not sups:
        return []
    out = []
    bases = sorted(sups)
    for i, base in enumerate(bases):
        # each supervisor's attempt counter is its OWN: scope its
        # events to the rank slice that node owns (base..next base),
        # or two nodes' identical attempt numbers would cross-match
        hi_base = bases[i + 1] if i + 1 < len(bases) else None
        node_ranks = {r: run for r, run in fleet["ranks"].items()
                      if r >= base and (hi_base is None or r < hi_base)}
        out += _stalls_for_supervisor(sups[base], node_ranks)
    return out


def _stalls_for_supervisor(sup, ranks):
    hang_attempts = [(ev["attempt"], ev.get("rank"))
                     for ev in sup["events"]
                     if ev.get("kind") == "elastic.restart"
                     and ev.get("failure") == "hang"
                     and ev.get("attempt") is not None]
    for ev in sup["events"]:
        # a hang that EXHAUSTS the restart budget gets no restart
        # event — and the terminal failure is exactly the one a
        # postmortem needs attributed. Its attempt index is the last
        # one any rank journaled.
        if ev.get("kind") == "elastic.budget_exhausted" and \
                ev.get("last_kind") == "hang":
            attempts = [a for run in ranks.values()
                        for a in (_attempt_of(run, i + 1)
                                  for i in range(len(run["run_starts"])))
                        if a is not None]
            if attempts:
                hang_attempts.append((max(attempts),
                                      ev.get("last_rank")))
    out = []
    for attempt, watchdog_rank in hang_attempts:
        last = {}
        for rank, run in ranks.items():
            steps = [s["step"] for s in run["steps"]
                     if isinstance(s.get("step"), int) and
                     _attempt_of(run, s.get("_incarnation")) == attempt]
            if steps:
                last[rank] = max(steps)
        if not last:
            continue
        lo, hi = min(last.values()), max(last.values())
        stalled = sorted(r for r, v in last.items() if v == lo)
        out.append({"kind": "hang", "attempt": attempt,
                    "rank": stalled[0], "ranks": stalled,
                    "last_step": lo, "gang_reached": hi,
                    "watchdog_rank": watchdog_rank,
                    "ambiguous": len(stalled) > 1 or lo == hi})
    return out


# -- summaries ---------------------------------------------------------------


def request_summary(run):
    """Serving columns over one run's ``request`` records: counts by
    state, total preemptions, exact p50/p99 TTFT/TPOT/e2e/queue (ms),
    and ``queue_share`` — the fraction of total TTFT spent in the
    arrival->admit queue (the reqtrace regression gate's signal: a
    p99 TTFT breach whose attribution shifted into queue wait moves
    this, a prefill regression doesn't). None when the run served
    nothing. (Canonical home of the summary ``tools/run_report.py``
    renders.)"""
    reqs = run.get("requests") or []
    if not reqs:
        return None
    out = {"requests": len(reqs),
           "finished": sum(1 for r in reqs
                           if r.get("state") == "FINISHED"),
           "cancelled": sum(1 for r in reqs
                            if r.get("state") == "CANCELLED"),
           "preemptions": sum(int(r.get("preemptions") or 0)
                              for r in reqs),
           "output_tokens": sum(int(r.get("output_tokens") or 0)
                                for r in reqs)}
    for key in ("ttft_ms", "tpot_ms", "e2e_ms", "queue_ms"):
        vals = [r[key] for r in reqs if _num(r.get(key))]
        if vals:
            out[f"{key}_p50"] = _pctl(vals, 50)
            out[f"{key}_p99"] = _pctl(vals, 99)
    both = [(r["queue_ms"], r["ttft_ms"]) for r in reqs
            if _num(r.get("queue_ms")) and _num(r.get("ttft_ms"))]
    ttft_total = sum(t for _, t in both)
    if both and ttft_total > 0:
        out["queue_share"] = sum(q for q, _ in both) / ttft_total
    return out


def merged_request_summary(fleet):
    """Request percentiles merged ACROSS serve replicas: the fleet's
    p50/p99 over every rank's request records pooled (per-replica
    percentiles don't average — the pool is the only correct merge)."""
    reqs = []
    for run in fleet["ranks"].values():
        reqs += run.get("requests") or []
    for sup in _supervisors(fleet).values():
        reqs += sup.get("requests") or []
    return request_summary({"requests": reqs})


def _supervisors(fleet):
    """Every supervisor run in the fleet dict (multi-node launches
    write one per node); tolerates pre-multi-node dicts carrying only
    the single ``supervisor`` slot."""
    sups = fleet.get("supervisors")
    if sups:
        return sups
    return {0: fleet["supervisor"]} if fleet.get("supervisor") else {}


def elastic_summary(run):
    """Elasticity columns over one run's ``elastic.*`` events (written
    by ``resilience.elastic.GangSupervisor``): restarts (budget-
    consuming crash/hang relaunches), budget-free preemptions, watchdog
    kills, resume-latency p50/max, the resume steps, and whether the
    restart budget was exhausted. None when the run was never
    supervised. (Canonical home of the summary ``tools/run_report.py``
    renders.)"""
    events = [e for e in run.get("events") or []
              if str(e.get("kind", "")).startswith("elastic.")]
    if not events:
        return None
    kinds = {}
    for e in events:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    resume_ms = [e["resume_ms"] for e in events
                 if e.get("kind") == "elastic.resumed"
                 and _num(e.get("resume_ms"))]
    out = {
        "restarts": kinds.get("elastic.restart", 0),
        "preemptions": kinds.get("elastic.preempt", 0),
        "watchdog_kills": kinds.get("elastic.watchdog_kill", 0),
        "preempt_signals": kinds.get("elastic.preempt_signal", 0),
        "budget_exhausted": bool(kinds.get("elastic.budget_exhausted")),
        "completed": bool(kinds.get("elastic.done")),
        "resume_steps": [e.get("resume_step") for e in events
                         if e.get("kind") in ("elastic.restart",
                                              "elastic.preempt")],
    }
    if resume_ms:
        out["resume_ms_p50"] = _pctl(resume_ms, 50)
        out["resume_ms_max"] = max(resume_ms)
    return out


def router_summary(run):
    """Serve-router columns over a run's ``router.*`` events (written
    by ``serving.fleet.Router``): the LAST ``router.summary`` truth
    (dispatched/requeued/rejected/completed, per-tenant token shares,
    aggregate p99 TTFT) plus reject/requeue/scale event counts. None
    when the run never routed. (Canonical home of the line
    ``tools/run_report.py`` / ``tools/fleet_report.py`` render.)"""
    if not run:
        return None
    events = [e for e in run.get("events") or []
              if str(e.get("kind", "")).startswith("router.")]
    if not events:
        return None
    summary = None
    for e in events:
        if e.get("kind") == "router.summary":
            summary = e   # last wins: the final truth
    out = {
        "dispatched": None, "requeued": None, "rejected": None,
        "completed": None, "replicas": None, "scale_ups": None,
        "scale_downs": None, "tenants": {}, "ttft_p99_ms": None,
        "requeue_events": sum(1 for e in events
                              if e.get("kind") == "router.requeue"),
        "reject_events": sum(1 for e in events
                             if e.get("kind") == "router.reject"),
        "scale_events": sum(1 for e in events
                            if e.get("kind") == "router.scale"),
    }
    if summary is not None:
        for k in ("dispatched", "requeued", "rejected", "completed",
                  "replicas", "scale_ups", "scale_downs",
                  "ttft_p99_ms"):
            out[k] = summary.get(k)
        out["tenants"] = summary.get("tenants") or {}
    return out


def slo_summary(run):
    """SLO columns over a run's ``slo.*`` events (written by
    ``obs.slo.SLOEvaluator``): the chronological fire/clear timeline,
    per-alert (``objective/severity``) fire/clear counts, which alerts
    are still latched at end-of-run, and the LAST ``slo.summary`` truth
    (budget remaining, burn). None when the run was never evaluated.
    (Canonical home of the timeline ``tools/slo_report.py`` renders.)
    """
    if not run:
        return None
    events = [e for e in run.get("events") or []
              if str(e.get("kind", "")).startswith("slo.")]
    if not events:
        return None
    timeline = []
    per = {}
    for e in events:
        kind = e.get("kind")
        if kind not in ("slo.fire", "slo.clear"):
            continue
        obj = e.get("objective")
        # keyed per (objective, severity): the page clearing must not
        # mask a warn that is still latched on the same objective
        alert = f"{obj}/{e.get('severity')}"
        row = per.setdefault(alert, {"fires": 0, "clears": 0,
                                     "active": False})
        if kind == "slo.fire":
            row["fires"] += 1
            row["active"] = True
        else:
            row["clears"] += 1
            row["active"] = False
        timeline.append({
            "at": e.get("at"), "kind": kind, "objective": obj,
            "severity": e.get("severity"),
            "burn_short": e.get("burn_short"),
            "burn_long": e.get("burn_long"),
            "windows": f"{e.get('window_short')}+"
                       f"{e.get('window_long')}",
            "threshold": e.get("threshold"),
            "worst_replica": e.get("worst_replica"),
            "budget_remaining": e.get("budget_remaining"),
        })
    timeline.sort(key=lambda r: (r["at"] is None, r["at"]))
    summary = None
    for e in events:
        if e.get("kind") == "slo.summary":
            summary = e   # last wins: the final truth
    out = {
        "fires": sum(r["fires"] for r in per.values()),
        "clears": sum(r["clears"] for r in per.values()),
        "active_at_end": sorted(a for a, r in per.items()
                                if r["active"]),
        "alerts": per,
        "timeline": timeline,
        "summary": None if summary is None
        else summary.get("objectives"),
        "ticks": None if summary is None else summary.get("ticks"),
    }
    return out


def tenant_summary(run):
    """Per-tenant chargeback columns over ONE journal: the run's
    ``request`` records rolled up via ``obs.usage.rollup_requests``
    (tokens, device-ns, page-ns, exact latency percentiles), plus the
    LAST ``tenant.summary`` (router truth) and LAST ``tenant.usage``
    (engine truth — the final incarnation's device-second telescoping
    and page-second closure) events carried alongside, with the
    fairness audit when the run routed. None when the run carries no
    tenant signal."""
    if not run:
        return None
    from . import usage as _usage

    reqs = run.get("requests") or []
    router = engine = None
    for e in run.get("events") or []:
        k = e.get("kind")
        if k == "tenant.summary":
            router = e   # last wins: the final truth
        elif k == "tenant.usage":
            engine = e   # last wins: the final incarnation
    if not reqs and router is None and engine is None:
        return None
    out = {
        "tenants": _usage.rollup_requests(reqs),
        "router": None if router is None else {
            "served_total": router.get("served_total"),
            "tenants": router.get("tenants") or {}},
        "engine": None if engine is None else {
            k: engine.get(k)
            for k in ("replica", "busy_ns", "prefill_ns", "decode_ns",
                      "page_bytes", "page_open", "seq_allocs",
                      "seq_frees", "tenants")},
    }
    if router is not None:
        out["fairness"] = _usage.fairness_audit(
            router.get("tenants") or {})
    return out


def merged_tenant_summary(fleet):
    """Chargeback rolled up ACROSS the fleet: every rank's (and the
    supervisors'/router's) request records pooled through ONE
    ``obs.usage.rollup_requests`` pass (percentiles over the pool —
    per-replica percentiles don't average), per-replica engine truth
    from each rank's LAST ``tenant.usage`` event, and the router's
    final ``tenant.summary`` + fairness audit when the run routed.
    None when nothing in the fleet carries a tenant signal."""
    from . import usage as _usage

    reqs = []
    replicas = {}
    for rank, run in sorted(fleet["ranks"].items()):
        reqs += run.get("requests") or []
        last = None
        for e in run.get("events") or []:
            if e.get("kind") == "tenant.usage":
                last = e   # last wins: the final incarnation
        if last is not None:
            replicas[rank] = {
                "replica": last.get("replica"),
                "busy_ns": last.get("busy_ns"),
                "page_open": last.get("page_open"),
                "tenants": last.get("tenants") or {}}
    for sup in _supervisors(fleet).values():
        reqs += sup.get("requests") or []
    rsum = None
    router_run = fleet.get("router")
    if router_run:
        reqs += router_run.get("requests") or []
        for e in router_run.get("events") or []:
            if e.get("kind") == "tenant.summary":
                rsum = e   # last wins: the final truth
    if not reqs and not replicas and rsum is None:
        return None
    out = {
        "tenants": _usage.rollup_requests(reqs),
        "replicas": replicas,
        "router": None if rsum is None else {
            "served_total": rsum.get("served_total"),
            "tenants": rsum.get("tenants") or {}},
    }
    if rsum is not None:
        out["fairness"] = _usage.fairness_audit(
            rsum.get("tenants") or {})
    return out


def per_rank_summary(run):
    """One rank's row in the fleet table (plain data)."""
    steps = run["steps"]
    times = [s["step_ms"] for s in steps
             if _num(s.get("step_ms")) and s["step_ms"] > 0]
    comm = [s["comm"].get("total_bytes", 0) for s in steps
            if isinstance(s.get("comm"), dict)]
    summ = run.get("summary") or {}
    hdr = run.get("header") or {}
    return {
        "rank": hdr.get("rank"),
        "steps": len(steps),
        "optimizer_steps": sum(int(s.get("steps_fused") or 1)
                               for s in steps),
        "last_step": max([s["step"] for s in steps
                          if isinstance(s.get("step"), int)],
                         default=None),
        "mean_step_ms": (sum(times) / len(times)) if times else None,
        "p50_step_ms": _pctl(times, 50),
        "goodput": summ.get("goodput"),
        "mfu": summ.get("mfu"),
        "examples_per_s": summ.get("examples_per_s"),
        "achieved_flops_per_s": summ.get("achieved_flops_per_s"),
        "comm_share": summ.get("comm_share"),
        "comm_bytes_per_step": (sum(comm) / len(comm)) if comm
        else None,
        "run_starts": len(run["run_starts"]),
        "requests": len(run.get("requests") or []),
        "anomalies": len(run.get("anomalies") or []),
        "parse_errors": len(run["parse_errors"]),
    }


def heartbeat_ages(run_dir, now=None):
    """Per-rank liveness proxy (seconds since the rank's journal file
    last flushed): crash-robust, needs no extra plumbing, and exactly
    what a router/autoscaler should alarm on. None for a rank whose
    journal vanished mid-read."""
    now = time.time() if now is None else float(now)
    out = {}
    for rank, p in sorted(rank_dirs(run_dir).items()):
        try:
            out[rank] = max(
                0.0, now - os.path.getmtime(os.path.join(p,
                                                         JOURNAL_FILE)))
        except OSError:
            out[rank] = None
    return out


def aggregate(run_dir, straggler_factor=1.5, straggler_patience=3):
    """The fleet rollup over ``run_dir``'s rank journals: per-rank
    table, cross-rank skew summary, straggler/hang attribution, gang
    goodput/MFU/throughput totals, merged request percentiles, and the
    supervisor's elasticity columns. Accepts a pre-loaded
    :func:`load_fleet` dict or a path."""
    fleet = run_dir if isinstance(run_dir, dict) else load_fleet(run_dir)
    aligned = align_steps(fleet)
    rows = step_skew(aligned)
    stragglers = detect_stragglers(
        rows, factor=straggler_factor, patience=straggler_patience)
    stragglers += stall_attribution(fleet)
    per_rank = {r: per_rank_summary(run)
                for r, run in fleet["ranks"].items()}
    worst = max(rows, key=lambda r: r["skew"] or 0.0) if rows else None
    slowest_counts = {}
    for row in rows:
        slowest_counts[row["slowest"]] = \
            slowest_counts.get(row["slowest"], 0) + 1
    skews = [r["skew"] for r in rows if r["skew"]]
    goodputs = [v["goodput"] for v in per_rank.values()
                if _num(v["goodput"])]
    exps = [v["examples_per_s"] for v in per_rank.values()
            if _num(v["examples_per_s"])]
    flops = [v["achieved_flops_per_s"] for v in per_rank.values()
             if _num(v["achieved_flops_per_s"])]
    mfus = [v["mfu"] for v in per_rank.values() if _num(v["mfu"])]
    comms = [v["comm_bytes_per_step"] for v in per_rank.values()
             if _num(v["comm_bytes_per_step"])]
    out = {
        "run_dir": fleet.get("run_dir"),
        "ranks": sorted(fleet["ranks"]),
        "nranks": len(fleet["ranks"]),
        "aligned_steps": len(aligned),
        "per_rank": per_rank,
        "skew": {
            "steps_compared": len(rows),
            "max": worst["skew"] if worst else None,
            "max_step": worst["step"] if worst else None,
            "mean": (sum(skews) / len(skews)) if skews else None,
            "worst_rank": worst["slowest"] if worst else None,
            "worst_rank_ratio": worst["slowest_vs_others"]
            if worst else None,
            "slowest_counts": slowest_counts,
        },
        "stragglers": stragglers,
        "goodput_min": min(goodputs) if goodputs else None,
        "goodput_mean": (sum(goodputs) / len(goodputs))
        if goodputs else None,
        "examples_per_s_total": sum(exps) if exps else None,
        "achieved_flops_per_s_total": sum(flops) if flops else None,
        "mfu_mean": (sum(mfus) / len(mfus)) if mfus else None,
        # gang-wide collective volume: the per-rank per-step means
        # summed (each rank's executable moves its own share)
        "comm_bytes_per_step_total": sum(comms) if comms else None,
        "requests": merged_request_summary(fleet),
        # one elasticity rollup across EVERY node's supervisor (counts
        # sum; a multi-node launch writes one supervisor_NN per node)
        "supervisor": elastic_summary(
            {"events": [e for sup in _supervisors(fleet).values()
                        for e in sup.get("events") or []]}),
        # the serve router's own journal (serving.fleet drill/serve):
        # dispatch/requeue/scale truth next to the per-rank rollup
        "router": router_summary(fleet.get("router")),
        # per-tenant chargeback: pooled request rollup + per-replica
        # engine truth + the router's fairness audit
        "tenant_usage": merged_tenant_summary(fleet),
    }
    if not isinstance(run_dir, dict):
        out["heartbeat_age_s"] = heartbeat_ages(run_dir)
    return out


# -- merged Chrome traces ----------------------------------------------------


def _remap_pid(pid, lane, device_pids):
    """A rank's host spans land on pid=lane; its device counter lanes
    keep their in-band slot but move into the lane's namespace slice —
    idempotent whether or not the exporting process already
    rank-namespaced them (the slot is recovered mod RANK_PID_STRIDE).
    A pid counts as a device lane only when the SOURCE file used it for
    counter samples (``device_pids``), never by magnitude alone: on
    hosts with ``pid_max`` raised past ``DEVICE_PID_BASE`` an
    un-namespaced export's host OS pid can exceed the device band."""
    if pid in device_pids and isinstance(pid, (int, float)):
        local = int(pid) % RANK_PID_STRIDE if pid < DEVICE_PID_BASE \
            else (int(pid) - DEVICE_PID_BASE) % RANK_PID_STRIDE
        return DEVICE_PID_BASE + lane * RANK_PID_STRIDE + local
    return lane


def merge_chrome_traces(run_dir, out_path, include_supervisor=True,
                        include_requests=True):
    """Fuse the per-rank Chrome traces under ``run_dir`` (exported next
    to each rank journal on close/postmortem when ``PADDLE_TPU_TRACE``
    is on) into ONE Perfetto file: rank r's spans on pid=r, its device
    counter lanes inside ``DEVICE_PID_BASE + r*RANK_PID_STRIDE``, the
    supervisor's spans on ``SUPERVISOR_PID`` — every rank a distinct
    lane, no pid collisions by construction. ``include_requests``
    additionally renders ``obs.reqtrace`` request lanes from the
    JOURNALS (slices on pid=replica with flow arrows across requeues)
    — journal-derived, so they appear even when the workers ran with
    span tracing off and contributed zero trace files. Returns
    ``{sources, events, request_slices, path}``."""
    sources = [(int(rank), None, os.path.join(p, TRACE_FILE))
               for rank, p in sorted(rank_dirs(run_dir).items())]
    if include_supervisor:
        for base, p in sorted(supervisor_dirs(run_dir).items()):
            sources.append((None, base, os.path.join(p, TRACE_FILE)))
    events = []
    n_sources = 0
    for rank, sup_base, path in sources:
        if not os.path.isfile(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (ValueError, OSError):
            continue
        lane = SUPERVISOR_PID + sup_base if rank is None else rank
        n_sources += 1
        evs = data.get("traceEvents") or []
        # the pids THIS export used for device counter samples — the
        # only reliable device-lane marker (see _remap_pid)
        device_pids = {e.get("pid") for e in evs if e.get("ph") == "C"}
        for ev in evs:
            ev = dict(ev)
            new_pid = _remap_pid(ev.get("pid"), lane, device_pids)
            if ev.get("ph") == "M" and \
                    ev.get("name") == "process_name" and \
                    new_pid == lane:
                continue  # the host lane gets ONE fleet-level meta below
            ev["pid"] = new_pid
            events.append(ev)
        if rank is None:
            label = "supervisor" if not sup_base \
                else f"supervisor (ranks {sup_base}+)"
        else:
            label = f"rank {rank:02d}"
        events.append({
            "ph": "M", "pid": lane, "name": "process_name",
            "args": {"name": label}})
        events.append({"ph": "M", "pid": lane, "name":
                       "process_sort_index",
                       "args": {"sort_index": lane}})
    labeled = {e["pid"] for e in events
               if e.get("ph") == "M" and e.get("name") == "process_name"}
    request_slices = 0
    if include_requests:
        from . import reqtrace as _reqtrace

        try:
            tls = _reqtrace.assemble_run(run_dir)
        except (FileNotFoundError, OSError):
            tls = {}
        req_events = _reqtrace.request_lane_events(tls)
        request_slices = sum(1 for e in req_events if e["ph"] == "X")
        events += req_events
        for pid in sorted({e["pid"] for e in req_events}):
            if pid in labeled:
                continue  # the rank's own trace already named the lane
            events.append({
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": f"replica {pid}"}})
            events.append({
                "ph": "M", "pid": pid, "name": "process_sort_index",
                "args": {"sort_index": pid}})
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                  default=str)
    return {"sources": n_sources, "events": len(events),
            "request_slices": request_slices, "path": out_path}
