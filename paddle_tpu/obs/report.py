"""Render the metrics registry for humans (table) or machines (JSON).

The reference prints its profiler report as a sorted per-op table
(``print_profiler`` in the C++ platform profiler); this is the same idea
over the ``obs.metrics`` registry: counters and gauges one per line,
histograms with count / mean / p50 / p90 / p99 / max, grouped by the
dotted instrument prefix (``executor.*``, ``dataloader.*``, ...).
"""
from __future__ import annotations

import json

from . import metrics as _metrics

__all__ = ["render", "render_json", "report"]


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".") if v else "0"
    return str(v)


def render(snapshot=None):
    """Aligned text table of one metrics snapshot (default: the live
    registry)."""
    snap = _metrics.snapshot() if snapshot is None else snapshot
    if not snap:
        return "(no instruments registered)"
    rows = []
    for name, val in snap.items():
        if isinstance(val, dict):  # histogram
            if not val.get("count"):
                rows.append((name, "(no samples)"))
                continue
            rows.append((name, (
                f"n={val['count']} mean={_fmt(val['mean'])} "
                f"p50={_fmt(val['p50'])} p90={_fmt(val['p90'])} "
                f"p99={_fmt(val['p99'])} max={_fmt(val['max'])}")))
        else:
            rows.append((name, _fmt(val)))
    width = max(len(n) for n, _ in rows)
    lines, prev_group = [], None
    for name, text in rows:
        group = name.split(".", 1)[0]
        if prev_group is not None and group != prev_group:
            lines.append("")
        prev_group = group
        lines.append(f"{name:<{width}}  {text}")
    return "\n".join(lines)


def render_json(snapshot=None, indent=1):
    snap = _metrics.snapshot() if snapshot is None else snapshot
    return json.dumps(snap, indent=indent, sort_keys=True, default=str)


def report(as_json=False, file=None):
    """Render the live registry; returns the string and additionally
    prints it to ``file`` when one is given."""
    text = render_json() if as_json else render()
    if file is not None:
        print(text, file=file)
    return text
