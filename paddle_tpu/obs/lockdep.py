"""Lockdep-style runtime lock-order validation for the host runtime.

The threaded host side (router/pool reader threads, the serve engine's
step lock, DataLoader workers, the async checkpoint writer, the run
journal) has a documented lock-ordering contract — router → pool →
replica, engine.step → scheduler → cache — but a contract nobody
*checks* is the PR-15 bug class waiting to recur. This module is the
runtime half of ``analysis/concurrency.py``'s static lint: the Linux
lockdep idea scaled down to the process — every instrumented lock
acquisition records, per thread, which lock *classes* (names, not
instances) were already held, building a process-wide acquisition-order
graph. The first edge that closes a cycle is the deadlock precondition
itself (an AB/BA pair needs only unlucky timing to hang), and it is
reported immediately — deterministically, on every run that merely
*exercises* both orders, long before the 1-in-10⁶ interleaving that
actually deadlocks:

- a **PTC004 diagnostic** with BOTH witness stacks (the acquisition
  that closed the cycle and the recorded stack of the reverse edge),
  raised as :class:`LockCycleError` (default) or warned
  (``PADDLE_TPU_LOCKDEP=warn``), journaled as a ``lockdep.cycle``
  event when a run journal is active, and kept in :func:`violations`
  so drills can assert emptiness;
- **held-time histograms** — ``lockdep.held_ms.<name>`` in the metrics
  registry — so a lock that quietly serializes the serve loop shows up
  in the same snapshot as every other SLO signal.

Zero overhead when off (the chaos/obs discipline): :func:`lock` /
:func:`rlock` are called once per lock *construction* and return plain
``threading.Lock()`` / ``RLock()`` unless lockdep is enabled — the
steady-state acquire path is untouched, no wrapper, no flag check.
Opt in per process with env ``PADDLE_TPU_LOCKDEP=1`` (raise on cycle)
or ``PADDLE_TPU_LOCKDEP=warn`` (record + warn), or at runtime with
:func:`enable` — runtime enabling instruments only locks constructed
afterwards, which is exactly what the drills want (scoped, no global
residue after :func:`disable` + :func:`reset`).

Lock classes are NAMES, not instances: every ``Scheduler`` shares the
class ``"serving.scheduler"``, so an ordering inversion between two
replicas' schedulers is caught even though the two runs touched
different objects — same-name nesting (two instances of one class) is
deliberately not an edge, mirroring lockdep's nested-class annotation
escape hatch.
"""
from __future__ import annotations

import os
import threading
import time
import traceback
import warnings

__all__ = [
    "LockCycleError", "enable", "disable", "enabled", "mode",
    "lock", "rlock", "violations", "order_graph", "held_names",
    "reset", "install_from_env",
]

MODE_RAISE = "raise"
MODE_WARN = "warn"

_mode = None           # None = off; MODE_RAISE | MODE_WARN

# process-wide order graph, guarded by a PLAIN lock (never instrumented:
# it is leaf-level by construction — nothing is acquired inside it)
_GRAPH_LOCK = threading.Lock()
_succ: dict = {}        # name -> set(names acquired while name held)
_edges: dict = {}       # (a, b) -> {"stack": [...], "thread": str, "count": n}
_violations: list = []  # PTC004 records, in detection order

_tls = threading.local()  # .held = [[name, lock_obj, t0, depth]], .busy


class LockCycleError(RuntimeError):
    """PTC004: a lock acquisition closed a cycle in the process-wide
    acquisition-order graph — the deadlock precondition. Carries the
    cycle (names, in order) and both witness stacks."""

    code = "PTC004"

    def __init__(self, cycle, new_stack, prev_stack, message):
        self.cycle = list(cycle)
        self.new_stack = new_stack
        self.prev_stack = prev_stack
        super().__init__(message)


def _held_stack():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack(skip=3, limit=12):
    """Bounded, rendered acquisition stack (the witness): drop the
    lockdep frames themselves, keep the caller's."""
    frames = traceback.extract_stack()[:-skip]
    return traceback.format_list(frames[-limit:])


def _find_path(src, dst, succ):
    """DFS: a path src -> ... -> dst over the order graph (names), or
    None. Iterative — the graph is small but a serve process is not the
    place to bet on recursion depth."""
    if src == dst:
        return [src]
    seen = {src}
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in sorted(succ.get(node, ())):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_edges(held_names_, name):
    """Record held -> name edges; returns a violation dict when one of
    them closed a cycle (graph mutation under _GRAPH_LOCK, everything
    observable — journal, metrics, raise — done by the CALLER outside
    it: emitting acquires instrumented locks, which would re-enter)."""
    viol = None
    new_stack = None
    with _GRAPH_LOCK:
        for h in held_names_:
            if h == name:
                continue  # same class nested: not an order edge
            key = (h, name)
            rec = _edges.get(key)
            if rec is not None:
                rec["count"] += 1
                continue
            if new_stack is None:
                new_stack = _stack(skip=4)
            # adding h -> name: a pre-existing path name -> ... -> h
            # means the new edge closes a cycle
            path = _find_path(name, h, _succ)
            _edges[key] = {"stack": new_stack,
                           "thread": threading.current_thread().name,
                           "count": 1}
            _succ.setdefault(h, set()).add(name)
            if path is not None and viol is None:
                prev = _edges.get((path[0], path[1])) if len(path) > 1 \
                    else None
                cycle = [h, name] + path[1:]
                viol = {
                    "code": "PTC004",
                    "cycle": cycle,
                    "new_edge": key,
                    "new_stack": new_stack,
                    "new_thread": threading.current_thread().name,
                    "prev_edge": (path[0], path[1])
                    if len(path) > 1 else None,
                    "prev_stack": (prev or {}).get("stack"),
                    "prev_thread": (prev or {}).get("thread"),
                }
                _violations.append(viol)
    return viol


def _emit_violation(viol):
    """Journal + metrics + warn/raise for one detected cycle. Runs with
    the edge-recording suppressed (the journal's own instrumented lock
    must not recurse into detection mid-report)."""
    from . import metrics as _metrics

    _metrics.counter("lockdep.cycles").inc()
    msg = ("[PTC004] lock-order cycle: "
           + " -> ".join(viol["cycle"])
           + f" (new edge {viol['new_edge'][0]} -> "
             f"{viol['new_edge'][1]} on thread "
             f"{viol['new_thread']})\n"
           + "acquisition closing the cycle:\n"
           + "".join(viol["new_stack"] or [])
           + "first recorded reverse-order acquisition"
           + (f" (thread {viol['prev_thread']}):\n" if
              viol.get("prev_thread") else ":\n")
           + "".join(viol.get("prev_stack") or ["  <unrecorded>\n"]))
    _tls.busy = True
    try:
        from . import journal as _journal

        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event(
                "lockdep.cycle", cycle=viol["cycle"],
                new_edge=list(viol["new_edge"]),
                new_thread=viol["new_thread"],
                prev_thread=viol.get("prev_thread"))
    except Exception:
        pass
    finally:
        _tls.busy = False
    if _mode == MODE_WARN:
        warnings.warn(msg, RuntimeWarning, stacklevel=4)
        return
    raise LockCycleError(viol["cycle"], viol["new_stack"],
                         viol.get("prev_stack"), msg)


class _DebugLock:
    """Instrumented wrapper over one ``threading.Lock``/``RLock``: edge
    recording + cycle check BEFORE blocking on the inner acquire (so a
    would-be deadlock raises instead of hanging), held-time histogram
    on the outermost release."""

    __slots__ = ("name", "_inner", "_reentrant", "_hist")

    def __init__(self, name, reentrant=False):
        self.name = str(name)
        self._reentrant = bool(reentrant)
        self._inner = threading.RLock() if reentrant \
            else threading.Lock()
        self._hist = None  # lazy: metrics import stays off constructors

    def acquire(self, blocking=True, timeout=-1):
        held = _held_stack()
        entry = None
        if self._reentrant:
            for e in held:
                if e[1] is self:
                    entry = e
                    break
        if entry is None and not getattr(_tls, "busy", False):
            names = []
            for e in held:
                if e[0] not in names:
                    names.append(e[0])
            if names:
                viol = _note_edges(names, self.name)
                if viol is not None:
                    _emit_violation(viol)  # warn-mode falls through
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if entry is not None:
                entry[3] += 1
            else:
                held.append([self.name, self, time.perf_counter(), 1])
        return ok

    def release(self):
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                held[i][3] -= 1
                if held[i][3] == 0:
                    t0 = held[i][2]
                    del held[i]
                    self._observe((time.perf_counter() - t0) * 1e3)
                break
        self._inner.release()

    def _observe(self, ms):
        h = self._hist
        if h is None:
            from . import metrics as _metrics

            h = self._hist = _metrics.histogram(
                "lockdep.held_ms." + self.name)
        h.observe(ms)

    def locked(self):
        # RLock has no locked() before 3.12; best-effort for plain Lock
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"_DebugLock({self.name!r}, reentrant={self._reentrant})"


# -- construction-time factories (the ONLY cost when off) --------------------

def lock(name):
    """A mutex for lock class ``name``: plain ``threading.Lock()`` when
    lockdep is off, an instrumented wrapper when on."""
    if _mode is None:
        return threading.Lock()
    return _DebugLock(name, reentrant=False)


def rlock(name):
    """A reentrant mutex for lock class ``name`` (same contract as
    :func:`lock`)."""
    if _mode is None:
        return threading.RLock()
    return _DebugLock(name, reentrant=True)


# -- control + introspection -------------------------------------------------

def enable(mode_=MODE_RAISE):
    """Instrument locks constructed from now on; ``mode_`` is
    ``"raise"`` (LockCycleError on the first cycle) or ``"warn"``."""
    global _mode
    if mode_ not in (MODE_RAISE, MODE_WARN):
        raise ValueError(f"lockdep mode must be raise|warn, got {mode_!r}")
    _mode = mode_


def disable():
    """Stop instrumenting NEW locks (already-wrapped ones keep
    recording; pair with :func:`reset` for a clean scoped window)."""
    global _mode
    _mode = None


def enabled():
    return _mode is not None


def mode():
    return _mode


def violations():
    """Every PTC004 cycle detected so far (list of dicts with the
    cycle, both edges, both witness stacks)."""
    with _GRAPH_LOCK:
        return list(_violations)


def order_graph():
    """{name: sorted successors} — the recorded acquisition order."""
    with _GRAPH_LOCK:
        return {a: sorted(bs) for a, bs in sorted(_succ.items())}


def held_names():
    """Lock classes the CURRENT thread holds, outermost first."""
    return [e[0] for e in _held_stack()]


def reset():
    """Clear the order graph and recorded violations (per-thread held
    stacks are live state and stay)."""
    with _GRAPH_LOCK:
        _succ.clear()
        _edges.clear()
        del _violations[:]


def install_from_env():
    """Adopt ``PADDLE_TPU_LOCKDEP`` (empty/0/false = off, ``warn`` =
    record+warn, anything else truthy = raise). Called at import."""
    v = os.environ.get("PADDLE_TPU_LOCKDEP", "").strip().lower()
    if v in ("", "0", "false", "off"):
        return
    enable(MODE_WARN if v == "warn" else MODE_RAISE)


install_from_env()
