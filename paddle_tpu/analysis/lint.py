"""Lint pass: API-level smells that are legal but almost always bugs.

TPU-native analog of the reference's inference analysis warnings
(``inference/analysis/analyzer.cc`` logging unused feed targets and the
``ir_graph_to_program_pass`` dropping orphaned nodes): none of these stop
compilation, but each one usually means the calling code is not doing
what its author thinks.
"""
from __future__ import annotations

from .diagnostics import WARNING
from .framework import AnalysisPass, op_reads

__all__ = ["LintPass", "lint_program"]


class LintPass(AnalysisPass):
    name = "lint"

    def run(self, ctx):
        blk, rep = ctx.block, ctx.report
        read = set()
        for op in ctx.ops:
            read.update(op_reads(op))

        # PTL101: declared feed slots nothing consumes
        for name, v in blk.vars.items():
            if v.is_data and name not in read \
                    and name not in ctx.fetch_names and name != "@lr":
                rep.add("PTL101", WARNING,
                        f"data var '{name}' is never read by any op and "
                        "never fetched; feeding it is dead weight",
                        var=name, pass_name=self.name)

        # PTL102: fetching a stale Variable handle. Needs the actual
        # handles the caller passed (a name always resolves to the
        # executed program's own var, which is trivially non-foreign).
        for f in ctx.fetch_vars:
            if not (hasattr(f, "block") and hasattr(f, "name")):
                continue
            foreign = f.block.program is not ctx.program
            if getattr(f, "_stale", False) or foreign:
                why = ("recorded in a different Program (the fetch resolves "
                       "by name against the executed program)" if foreign
                       else "marked stale")
                rep.add("PTL102", WARNING,
                        f"fetched variable '{f.name}' is {why}; its "
                        "shape/semantics may have diverged from the handle "
                        "you hold", var=f.name, pass_name=self.name)

        # PTL103: captured constants nothing consumes
        for name in ctx.program._constants:
            if name not in read and name not in ctx.fetch_names:
                rep.add("PTL103", WARNING,
                        f"constant '{name}' was captured into the program "
                        "but no op consumes it", var=name,
                        pass_name=self.name)


def lint_program(program, fetch_list=(), ops=None):
    """Run only the lint pass; returns the DiagnosticReport."""
    from .framework import PassContext, normalize_fetch

    fetch_names, fetch_vars = normalize_fetch(fetch_list)
    ctx = PassContext(program, ops=ops, fetch_names=fetch_names,
                      fetch_vars=fetch_vars)
    LintPass().run(ctx)
    return ctx.report
