"""Diagnostics: per-pass findings with op/var provenance.

TPU-native analog of the reference's pass error plumbing
(``paddle/fluid/framework/ir/pass.h`` PADDLE_ENFORCE messages + the
``inference/analysis`` AnalysisPass reporting): every check emits a coded
Diagnostic instead of raising ad hoc, so the Executor can decide whether a
finding is fatal, the CLI can print a report, and tests can assert exact
codes.

Error codes (``PTA*`` = verifier, ``PTL*`` = lint):

==========  =========  =====================================================
code        severity   meaning
==========  =========  =====================================================
PTA001      error      use-before-def: op reads a var no prior op defined
PTA002      error      dangling input: op reads a name the block never declared
PTA003      error      duplicate output: one op writes the same name twice
PTA004      error      WAW clobber: ``assign_to`` overwrites a value no op read
PTA005      error      shape drift: re-inferred op output shape != recorded aval
PTA006      error      dtype drift: re-inferred op output dtype != recorded aval
PTA007      error      donation hazard: donated persistable read after last write
PTA008      warning    shape re-inference failed for an op (cannot cross-check)
PTA009      warning    fed shape mismatches a declared static (non -1) dim
PTA010      warning    WAW clobber between ordinary (non-assign) ops
PTA011      error      use-after-donate: two persistables share one Scope
                       buffer and one is donated (fused windows re-read it)
PTA012      warning    plan/spec mismatch: a feed/fetch/persistable sharding
                       spec is inconsistent with the installed ShardingPlan
PTA013      error      over-budget layout: a planner candidate's per-device
                       peak HBM exceeds the budget (candidate is infeasible)
PTL101      warning    feed/data var never read by any op and never fetched
PTL102      warning    fetch of a stale Variable handle (other Program / _stale)
PTL103      warning    captured constant never consumed
PTL104      warning    remat candidate: a long-lived, cheap-to-recompute
                       activation holds up the peak-HBM high-water mark
PTC001      error      inconsistent lock-acquisition order across methods
                       (A->B on one path, B->A on another: deadlock
                       precondition) — ``analysis/concurrency.py``
PTC002      error      blocking call (sleep / Thread.join / Popen.wait /
                       urlopen / untimed queue.get) under a held lock
PTC003      warning    attribute written from both a spawned-thread target
                       and a public method without a shared lock in scope
PTC004      error      runtime lock-order cycle witnessed by the lockdep
                       validator (``obs/lockdep.py``, both stacks attached)
==========  =========  =====================================================
"""
from __future__ import annotations

__all__ = ["Diagnostic", "DiagnosticReport", "ProgramVerificationError",
           "ERROR", "WARNING", "CONCURRENCY_CODES"]

ERROR = "error"
WARNING = "warning"

# PTC00x remediation hints, keyed by code — the concurrency lint
# (static: PTC001-003) and the lockdep runtime (PTC004) print these
# next to findings; tools/lint_concurrency.py renders them in reports.
CONCURRENCY_CODES = {
    "PTC001": (ERROR, "pick ONE acquisition order for the two locks, "
               "document it in the module docstring, and restructure the "
               "minority path (or split the critical section)"),
    "PTC002": (ERROR, "move the blocking call outside the critical "
               "section — snapshot state under the lock, block after "
               "release — or bound it with a timeout"),
    "PTC003": (WARNING, "guard both the thread-target write and the "
               "public-method write with one shared lock, or hand the "
               "value across via a queue/Event instead of an attribute"),
    "PTC004": (ERROR, "a runtime acquisition closed an order cycle: fix "
               "the minority ordering shown in the witness stacks, then "
               "re-run the drill under PADDLE_TPU_LOCKDEP=1"),
}


class Diagnostic:
    """One finding: code + severity + message + provenance (which op index /
    op repr / var name it anchors to, and which pass emitted it)."""

    __slots__ = ("code", "severity", "message", "op_idx", "op", "var",
                 "pass_name")

    def __init__(self, code, severity, message, op_idx=None, op=None,
                 var=None, pass_name=None):
        self.code = code
        self.severity = severity
        self.message = message
        self.op_idx = op_idx
        self.op = op
        self.var = var
        self.pass_name = pass_name

    def __repr__(self):
        where = []
        if self.op_idx is not None:
            where.append(f"op#{self.op_idx}")
        if self.op is not None:
            where.append(f"{self.op.type}")
        if self.var is not None:
            where.append(f"var '{self.var}'")
        loc = " @ " + " ".join(where) if where else ""
        return f"[{self.code}] {self.severity}{loc}: {self.message}"


class DiagnosticReport:
    """Ordered collection of Diagnostics for one program + pass run."""

    def __init__(self, program=None):
        self.program = program
        self.diagnostics: list[Diagnostic] = []
        self.pass_stats: dict[str, dict] = {}  # pass name -> {'removed': n, ...}

    def add(self, code, severity, message, op_idx=None, op=None, var=None,
            pass_name=None):
        d = Diagnostic(code, severity, message, op_idx=op_idx, op=op, var=var,
                       pass_name=pass_name)
        self.diagnostics.append(d)
        return d

    def errors(self):
        return [d for d in self.diagnostics if d.severity == ERROR]

    def warnings(self):
        return [d for d in self.diagnostics if d.severity == WARNING]

    def codes(self):
        return [d.code for d in self.diagnostics]

    def has(self, code):
        return any(d.code == code for d in self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def raise_if_errors(self):
        errs = self.errors()
        if errs:
            raise ProgramVerificationError(errs, self)
        return self

    def __str__(self):
        lines = [f"DiagnosticReport: {len(self.errors())} error(s), "
                 f"{len(self.warnings())} warning(s)"]
        lines += [f"  {d!r}" for d in self.diagnostics]
        for name, stats in self.pass_stats.items():
            kv = ", ".join(f"{k}={v}" for k, v in stats.items())
            lines.append(f"  pass {name}: {kv}")
        return "\n".join(lines)


class ProgramVerificationError(RuntimeError):
    """Raised when the verifier finds error-severity diagnostics. Carries
    the full report so callers (and tests) can inspect exact codes."""

    def __init__(self, errors, report):
        self.errors = errors
        self.report = report
        msg = "\n".join(repr(d) for d in errors)
        super().__init__(
            f"Program verification failed with {len(errors)} error(s):\n{msg}")
