"""paddle_tpu.analysis — Program-IR verifier + optimization pass framework.

TPU-native analog of the reference's ``paddle/fluid/framework/ir`` pass
infrastructure and the ``inference/analysis`` Analyzer: the recorded
Program (``static_/program.py``) is checked and rewritten HERE, before
``Executor._compile`` hands it to ``jax.jit`` — so a malformed graph dies
with an op/var-anchored diagnostic instead of an opaque XLA trace error,
and ops XLA would never need are dropped before they cost trace and
compile time.

Layers:

- ``diagnostics``  — coded findings (PTA*/PTL*) with op/var provenance
- ``framework``    — Pass / AnalysisPass / RewritePass / PassManager
- ``verifier``     — use-before-def, dangling inputs, WAW hazards,
                     eval_shape re-inference, donation safety
- ``dataflow``     — def-use chains, versioned liveness intervals,
                     Executor-side donation-race / plan-consistency checks
- ``memory``       — per-op liveness walk: peak-HBM prediction, remat
                     candidates (validated against ``memory_analysis()``)
- ``passes``       — identity forwarding, dead-op elimination, CSE
- ``lint``         — API-smell warnings (unused feeds, stale fetches,
                     unconsumed constants)
- ``concurrency``  — host-runtime concurrency lint over the package's own
                     Python source (PTC001 lock-order inversions, PTC002
                     blocking-under-lock, PTC003 unguarded cross-thread
                     writes); the static half of ``obs.lockdep``

``run_compile_passes`` is the Executor's single entry point: verify
always, optimize behind ``optimize_level``.
"""
from __future__ import annotations

from .diagnostics import (Diagnostic, DiagnosticReport,
                          ProgramVerificationError, ERROR, WARNING)
from .framework import (AnalysisPass, Pass, PassContext, PassManager,
                        RewritePass, normalize_fetch, op_reads, op_writes)
from .verifier import VerifierPass, verify_program
from .passes import (CSEPass, DeadOpEliminationPass, ForwardIdentityPass,
                     default_optimize_passes)
from .lint import LintPass, lint_program
from . import concurrency
from . import dataflow
from . import memory
from .memory import (MemoryEstimate, estimate_entry, memory_report,
                     remat_candidates)

__all__ = [
    "Diagnostic", "DiagnosticReport", "ProgramVerificationError",
    "Pass", "AnalysisPass", "RewritePass", "PassContext", "PassManager",
    "normalize_fetch", "VerifierPass", "verify_program",
    "ForwardIdentityPass", "DeadOpEliminationPass", "CSEPass",
    "default_optimize_passes", "LintPass", "lint_program",
    "concurrency",
    "run_compile_passes", "dataflow", "memory", "MemoryEstimate",
    "estimate_entry", "memory_report", "remat_candidates",
]


def run_compile_passes(program, fetch_list=(), feed_shapes=None,
                       donated=None, scope_names=None, optimize_level=0,
                       infer_shapes=True, raise_on_error=True):
    """Verify ``program`` (always) and optimize its op list (behind
    ``optimize_level``); returns ``(ops, report)`` where ``ops`` is the
    (possibly rewritten) op list to compile. The Program itself is never
    mutated.
    """
    fetch_names, fetch_vars = normalize_fetch(fetch_list)
    ctx = PassContext(program, fetch_names=fetch_names,
                      feed_shapes=feed_shapes, donated=donated,
                      scope_names=scope_names, fetch_vars=fetch_vars)
    PassManager([VerifierPass(infer_shapes=infer_shapes),
                 LintPass()]).run_ctx(ctx)
    if raise_on_error:
        ctx.report.raise_if_errors()
    # rewrites only run on a verified program
    if not ctx.report.errors():
        PassManager(default_optimize_passes(optimize_level)).run_ctx(ctx)
    return ctx.ops, ctx.report
