"""Optimization passes: identity forwarding, DCE, CSE.

TPU-native analog of the reference's ``framework/ir`` graph passes
(``graph_pattern_detector.cc`` rewrites, the inference
``ir_graph_clean_pass`` / ``simplify_with_basic_ops_pass`` that strips
neutered dropout, and memory-reuse analysis): XLA already fuses and CSEs
*within* the compiled executable, but ops we never hand to XLA cost zero
trace time, zero compile time, and zero HBM — and a smaller replayed
program is what keeps `jax.jit` compilation latency bounded as recorded
programs grow.

All passes are list-to-list rewrites over ``ctx.ops`` (the input Program
is never mutated) and keep every write to a *protected* name — fetches,
persistables, data slots — so fetched values and Scope state are bitwise
identical to the unoptimized replay.
"""
from __future__ import annotations

from .framework import RewritePass, op_reads

__all__ = ["ForwardIdentityPass", "DeadOpEliminationPass", "CSEPass",
           "default_optimize_passes"]


def _is_identity(op):
    """Ops provably equal to forwarding their first input unchanged.

    ``clone(for_test=True)`` neuters dropout by rewriting ``p`` to 0.0:
    the kernel then draws an all-true mask and returns ``x / 1.0`` —
    bitwise ``x``, but still tracing an RNG + select into the executable.
    """
    if op.type in ("dropout", "dropout_axes", "alpha_dropout"):
        return float(op.attrs.get("p", 1.0)) == 0.0
    return False


class ForwardIdentityPass(RewritePass):
    """Rewire consumers of an identity op's output to read its input, then
    drop the op (ref: simplify_with_basic_ops_pass dropping eval-mode
    dropout). Protected outputs keep the op (the name must still be
    written for fetch/Scope visibility)."""

    name = "forward_identity"

    def rewrite(self, ctx):
        protected = ctx.protected_names()
        last_write = _last_write_index(ctx.ops)
        rename: dict[str, str] = {}
        out = []
        for idx, op in enumerate(ctx.ops):
            if op.input_names:
                op = _remap_inputs(op, rename)
            if (_is_identity(op) and len(op.output_names) == 1
                    and op.output_names[0] not in protected
                    and op.input_names and op.input_names[0] is not None):
                # inputs were remapped above, so src already chases chains
                src = op.input_names[0]
                tgt = op.output_names[0]
                # forwarding is only sound if nothing overwrites the
                # source later: readers of tgt would see the NEW value
                # (assign_to can redefine any name in-place)
                if last_write.get(src, -1) < idx:
                    rename[tgt] = src
                    continue
            # a write to a forwarded name ends the forwarding
            for n in op.output_names:
                rename.pop(n, None)
            out.append(op)
        return out


class DeadOpEliminationPass(RewritePass):
    """Reverse-liveness DCE: an op survives only if some output reaches a
    fetch or a persistable's final value (ref: ir_graph_clean_pass +
    inference ir "delete unused nodes"). Kernels here are pure, so an
    unreachable op is unobservable by construction."""

    name = "dead_op_elimination"

    def rewrite(self, ctx):
        blk = ctx.block
        live = set(ctx.fetch_names)
        for name, v in blk.vars.items():
            if v.persistable:
                live.add(name)
        keep = [False] * len(ctx.ops)
        for i in range(len(ctx.ops) - 1, -1, -1):
            op = ctx.ops[i]
            if any(n in live for n in op.output_names):
                keep[i] = True
                live.difference_update(op.output_names)
                live.update(op_reads(op))
        return [op for op, k in zip(ctx.ops, keep) if k]


class CSEPass(RewritePass):
    """Common-subexpression elimination keyed on
    ``(op.type, input value-versions, attrs)`` for pure registry kernels.

    Purity here is structural: the op's fn must be exactly the kernel the
    registry maps its type to (hand-built closures — optimizer updates,
    grad clip — are skipped), and stochastic kernels are still safe to
    merge because their PRNG key is an explicit captured-constant input,
    part of the key. Input *versions* (bumped at every write) keep two
    textually equal ops distinct when an ``assign_to`` redefines a name
    between them.
    """

    name = "cse"

    def rewrite(self, ctx):
        from ..ops._base import OP_REGISTRY

        protected = ctx.protected_names()
        last_write = _last_write_index(ctx.ops)
        version: dict[str, int] = {}
        seen: dict[tuple, list] = {}
        rename: dict[str, str] = {}
        out = []
        for idx, op in enumerate(ctx.ops):
            if op.input_names:
                op = _remap_inputs(op, rename)
            key = None
            if (OP_REGISTRY.get(op.type) is op.fn
                    and not any(n in protected for n in op.output_names)):
                try:
                    akey = tuple(sorted(
                        (k, repr(v)) for k, v in op.attrs.items()))
                    key = (op.type,
                           tuple((n, version.get(n, 0))
                                 for n in op.input_names),
                           akey)
                except Exception:  # unorderable attrs: skip CSE for this op
                    key = None
            if key is not None and key in seen:
                cached = seen[key]
                # the cached outputs must still hold the value they held
                # when registered (no in-place write since), AND nothing
                # may overwrite them later — readers of the merged-away
                # name would see the clobbered value
                if all(version.get(n, 0) == v
                       and last_write.get(n, -1) < idx for n, v in cached):
                    for mine, (theirs, _) in zip(op.output_names, cached):
                        rename[mine] = theirs
                    continue
            for n in op.output_names:
                version[n] = version.get(n, 0) + 1
                rename.pop(n, None)  # in-place write ends any forwarding
            if key is not None:
                seen[key] = [(n, version.get(n, 0))
                             for n in op.output_names]
            out.append(op)
        return out


def _last_write_index(ops):
    """name -> index of the LAST op writing it (forwarding-safety guard)."""
    out: dict[str, int] = {}
    for idx, op in enumerate(ops):
        for n in op.output_names:
            out[n] = idx
    return out


def _remap_inputs(op, rename):
    if not rename or not any(n in rename for n in op.input_names if n):
        return op
    from ..static_.program import Operator

    new_in = [rename.get(n, n) if n is not None else None
              for n in op.input_names]
    return Operator(op.type, op.fn, new_in, list(op.output_names),
                    op.attrs)


def default_optimize_passes(optimize_level):
    """Pass pipeline for an ``optimize_level`` (documented on
    ``Executor.run``): 0 = none, 1 = identity forwarding + DCE (always
    semantics-preserving), 2 = additionally CSE."""
    passes = []
    if optimize_level >= 1:
        passes += [ForwardIdentityPass(), DeadOpEliminationPass()]
    if optimize_level >= 2:
        passes.append(CSEPass())
    return passes
