"""Static dataflow: def-use chains and versioned liveness over a Program.

TPU-native analog of the reference's ``framework/ir`` memory-optimize
prepasses (``memory_optimize_pass.cc`` builds exactly this — per-var
def/use indices and live ranges over the op list — before it reuses
buffers): the recorded ``Block.ops`` list is already in program order
and name-linked, so dataflow here is a single forward walk, not graph
surgery.

Names are **versioned**: every write to a name opens a new ``VarLife``
and closes the previous one (an ``assign_to`` clobber or a WAW pair is
two distinct values that happen to share a name — their live ranges
must not be merged, or the first value looks live across the clobber
and every peak-memory number downstream inflates).

The walk understands the executor's value classes:

- **feeds / constants / scope-held persistables** exist before op 0
  (version 0, ``def_idx == ENTRY``). ``@comm@*`` exchange state and
  ``<param>@OPT@<k>`` optimizer slots are ordinary persistables here —
  they ride the donated carry like any parameter.
- **donated persistables** (the ones the program re-emits): donation
  requires the last write to end the entry buffer's life, so the entry
  version is flagged ``donated`` and the verifier's PTA007 enforces
  that no read follows the last write.
- **fetches and re-emitted persistables** are live-out: their final
  version extends to ``n_ops`` (the executor reads fetches and
  restores persistables into the Scope after the replay).
- **fused ``run_steps`` windows** (``steps=K``): the op list is one
  scan *body*; persistables are the donated carry (live across the
  whole body and every iteration), while feed/fetch buffers stack K
  copies — recorded as ``Liveness.steps`` for ``analysis.memory`` to
  scale the entry/exit classes by.

``check_donation_races`` and ``check_plan_consistency`` are the
Executor-side verifier checks (they need the live Scope / the installed
ShardingPlan, which the pure-Program passes never see); the Executor
runs them per compile and folds their diagnostics into the same report
``run_compile_passes`` produced.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .diagnostics import ERROR, WARNING
from .framework import op_reads

__all__ = ["ENTRY", "VarLife", "Liveness", "def_use", "analyze",
           "check_donation_races", "check_plan_consistency"]

ENTRY = -1  # def_idx of values that exist before op 0 executes


@dataclasses.dataclass
class VarLife:
    """One version of one name: its defining write, last read, and the
    executor value class it belongs to."""

    name: str
    version: int        # 0 = entry value, +1 per write to the name
    def_idx: int        # ENTRY, or the index of the defining op
    last_use: int       # last op index reading this version (== def_idx
    #                     when never read; == n_ops when live at exit)
    writer: str | None  # defining op type (None for entry values)
    kind: str           # "feed" | "persistable" | "constant" | "temp"
    nbytes: int
    donated: bool = False   # entry buffer the executor donates
    live_out: bool = False  # fetched / restored into the Scope

    @property
    def span(self):
        """Ops this version stays live across (0 = consumed where
        defined)."""
        return max(0, self.last_use - max(self.def_idx, 0))


class Liveness:
    """All VarLife intervals of one op list, plus the walk's context."""

    def __init__(self, lives, n_ops, fetch_names, donated, steps=None):
        self.lives = list(lives)
        self.n_ops = int(n_ops)
        self.fetch_names = tuple(fetch_names)
        self.donated = frozenset(donated)
        self.steps = steps  # fused-window K (None = single step)

    def intervals(self, name):
        return [l for l in self.lives if l.name == name]

    def temps(self):
        """Intermediate values (not entry-resident, not live-out):
        the buffers whose lifetime the memory walk can actually
        overlap."""
        return [l for l in self.lives
                if l.kind == "temp" and not l.live_out]

    def live_at(self, idx):
        """Temp versions live during op ``idx`` (inclusive interval:
        an op's inputs and outputs coexist while it executes — the
        convention XLA's buffer assignment also charges)."""
        return [l for l in self.temps()
                if l.def_idx <= idx <= l.last_use]

    def table(self):
        """CLI rows: (name, version, kind, def, last_use, bytes,
        flags)."""
        rows = []
        for l in sorted(self.lives,
                        key=lambda x: (max(x.def_idx, -1), x.name)):
            flags = "".join((
                "D" if l.donated else "", "O" if l.live_out else ""))
            rows.append((l.name, l.version, l.kind,
                         "entry" if l.def_idx == ENTRY else l.def_idx,
                         "exit" if l.last_use >= self.n_ops else l.last_use,
                         l.nbytes, flags))
        return rows


def _var_nbytes(program, name, feed_shapes=None):
    if feed_shapes and name in feed_shapes:
        shape, dt = feed_shapes[name]
        n = 1
        for s in shape:
            n *= int(s)
        return n * int(np.dtype(dt).itemsize)
    if name in program._constants:
        c = program._constants[name]
        n = 1
        for s in c.shape:
            n *= int(s)
        return n * int(np.dtype(c.dtype).itemsize)
    v = program.global_block.vars.get(name)
    if v is None:
        return 0
    n = 1
    for s in v._data.shape:
        n *= int(s)
    return n * int(np.dtype(v._data.dtype).itemsize)


def def_use(ops):
    """Def-use chains over an op list: ``(defs, uses)`` where
    ``defs[name]`` is every op index writing the name (program order)
    and ``uses[name]`` every op index reading it."""
    defs, uses = {}, {}
    for i, op in enumerate(ops):
        for n in op_reads(op):
            uses.setdefault(n, []).append(i)
        for n in op.output_names:
            defs.setdefault(n, []).append(i)
    return defs, uses


def analyze(program, ops=None, fetch_names=(), feed_shapes=None,
            scope_names=None, donated=None, steps=None):
    """Versioned liveness of ``program`` (see module docstring).

    ``feed_shapes`` maps fed names to ``(shape, dtype)`` when the
    actual feed surface is known (the Executor knows; a CLI previewing
    a bare Program falls back to every declared data var). ``donated``
    overrides the inferred donation set (default: scope-held
    persistables the op list re-emits — exactly what
    ``Executor._compile`` donates)."""
    blk = program.global_block
    ops = list(ops if ops is not None else blk.ops)
    fetch_names = tuple(fetch_names)

    written = set()
    for op in ops:
        written.update(op.output_names)

    entry_kind = {}
    for name in program._constants:
        entry_kind[name] = "constant"
    if feed_shapes is not None:
        for name in feed_shapes:
            entry_kind[name] = "feed"
    for name, v in blk.vars.items():
        if v.is_data and feed_shapes is None:
            entry_kind[name] = "feed"
        elif v.persistable and name not in entry_kind:
            if scope_names is None or name in scope_names:
                entry_kind[name] = "persistable"
    if donated is None:
        donated = [n for n, k in entry_kind.items()
                   if k == "persistable" and n in written]
    donated = frozenset(donated)

    def nbytes(name):
        return _var_nbytes(program, name, feed_shapes)

    cur: dict[str, VarLife] = {}
    finished: list[VarLife] = []
    versions: dict[str, int] = {}
    for name, kind in entry_kind.items():
        cur[name] = VarLife(name, 0, ENTRY, ENTRY, None, kind,
                            nbytes(name), donated=name in donated)

    persist_names = {n for n, v in blk.vars.items() if v.persistable}
    for i, op in enumerate(ops):
        # reads first: an op reading and writing one name (optimizer
        # updates) reads the OLD version
        for n in op_reads(op):
            life = cur.get(n)
            if life is not None:
                life.last_use = i
        for n in op.output_names:
            prev = cur.pop(n, None)
            if prev is not None:
                finished.append(prev)
            versions[n] = versions.get(n, 0) + 1
            kind = "persistable" if n in persist_names else "temp"
            cur[n] = VarLife(n, versions[n], i, i, op.type, kind,
                             nbytes(n))

    n_ops = len(ops)
    for n, life in cur.items():
        if n in fetch_names or (life.kind == "persistable"
                                and life.def_idx != ENTRY):
            # fetches leave through the output tuple; re-emitted
            # persistables are restored into the Scope after the replay
            life.live_out = True
            life.last_use = n_ops
        finished.append(life)
    return Liveness(finished, n_ops, fetch_names, donated, steps=steps)


# -- Executor-side verifier checks -------------------------------------------

_CHECK = "executor-verifier"


def check_donation_races(report, scope, updated, frozen):
    """PTA011: two persistable names bound to the SAME buffer in the
    Scope while at least one is donated. The executor donates every
    ``updated`` buffer to XLA — the dispatch invalidates it — so the
    alias's reads are use-after-free. On the fused ``run_steps`` path
    this is the cross-window race: the carry donates once per window
    while every scan iteration re-reads the dead alias. Scope aliasing
    only arises host-side (two ``scope.set`` calls sharing one array),
    which is why this check lives at compile time WITH the Scope, not
    in the pure-Program verifier."""
    updated = tuple(updated)
    donated = set(updated)
    seen: dict[int, str] = {}
    for name in tuple(updated) + tuple(frozen):
        arr = scope.find_var(name)
        if arr is None:
            continue
        other = seen.get(id(arr))
        if other is None:
            seen[id(arr)] = name
            continue
        if name in donated or other in donated:
            report.add(
                "PTA011", ERROR,
                f"persistables '{other}' and '{name}' share one device "
                f"buffer and "
                f"'{other if other in donated else name}' is donated: "
                "the first dispatch deletes the buffer and every later "
                "read of the alias (each iteration of a fused "
                "run_steps window) is use-after-donate. Install "
                "distinct arrays in the Scope.",
                var=name, pass_name=_CHECK)
        seen[id(arr)] = name
    return report


def _spec_axes(spec):
    for part in tuple(spec or ()):
        for ax in (part if isinstance(part, tuple) else (part,)):
            if ax is not None:
                yield ax


def check_plan_consistency(report, plan, feed_names, shapes, fetch_names,
                           scope):
    """PTA012: feed/fetch sharding specs inconsistent with the installed
    ShardingPlan. All warnings (the Executor's documented fallback is to
    replicate), but each one means the plan is not doing what the
    planner chose:

    - the plan shards a feed this entry does not feed (the plan was
      built against a different feed surface);
    - a declared feed spec does not fit the CONCRETE fed shape (the
      axis no longer divides — the feed silently replicates, so the
      'data-parallel' entry computes the full batch per device);
    - a persistable's plan spec no longer fits the Scope array's shape
      (silent replicated fallback — HBM per device is the plan's
      number times the shard factor);
    - a fetch targets a model-sharded persistable (the replicated
      out_sharding gathers the full array every step).
    """
    feed_shapes = dict(zip(feed_names, [s for s, _ in shapes]))
    for name, spec in (plan.feed_specs or {}).items():
        if tuple(spec or ()) and name not in feed_shapes:
            report.add(
                "PTA012", WARNING,
                f"plan shards feed '{name}' over {tuple(spec)} but this "
                "entry does not feed it — was the plan built for a "
                "different feed surface?",
                var=name, pass_name=_CHECK)
    for name, shape in feed_shapes.items():
        declared = tuple((plan.feed_specs or {}).get(name) or ())
        if declared and plan.feed_spec_for(name, shape) == ():
            report.add(
                "PTA012", WARNING,
                f"plan spec {declared} for feed '{name}' does not fit "
                f"the fed shape {tuple(shape)}: the feed silently "
                "replicates and the data axis goes unused",
                var=name, pass_name=_CHECK)
    for name in (plan.param_specs or {}):
        arr = scope.find_var(name)
        if arr is None:
            continue
        declared = tuple(plan.param_specs.get(name) or ())
        if declared and plan.spec_for(name, tuple(arr.shape)) == ():
            report.add(
                "PTA012", WARNING,
                f"plan spec {declared} for persistable '{name}' does "
                f"not fit its Scope shape {tuple(arr.shape)}: the "
                "buffer silently replicates (per-device HBM is the "
                "plan's estimate times the lost shard factor)",
                var=name, pass_name=_CHECK)
    for name in fetch_names:
        spec = tuple((plan.param_specs or {}).get(name) or ())
        if any(ax != "data" for ax in _spec_axes(spec)):
            report.add(
                "PTA012", WARNING,
                f"fetch of model-sharded persistable '{name}' (spec "
                f"{spec}): the replicated fetch gathers the full array "
                "on every step",
                var=name, pass_name=_CHECK)
    return report
