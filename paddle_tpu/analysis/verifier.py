"""Program verifier: structural + shape/dtype checks before compilation.

TPU-native analog of the reference's graph validation spread across
``framework/ir/graph_helper.cc`` (HasCircle / topology checks),
``framework/op_desc.cc`` InferShape, and the AnalysisPredictor's
``inference/analysis`` IR passes: a malformed recorded Program must be
rejected HERE with an op/var-anchored diagnostic, not surface as an opaque
XLA trace error (or silently wrong numerics) inside ``Executor._compile``.

Checks (codes documented in ``diagnostics.py``):

- PTA001 use-before-def   — an op reads a name no entry value (feed /
  constant / scope-held persistable) provides and no earlier op wrote.
- PTA002 dangling input   — an op reads a name the block never declared.
- PTA003 duplicate output — one op lists the same output name twice; the
  replay env would silently keep only the last value.
- PTA004 WAW clobber      — an ``assign_to`` (``Variable.set_value`` /
  ``layers.assign(out=...)``) overwrites an earlier OP's output that
  nothing ever read: the first computation is silently lost.
  (The same hazard between two ordinary ops is PTA010, warning: dead
  writes are wasteful but the last-write-wins replay is deterministic.)
- PTA005/6 shape/dtype drift — every op is re-inferred with
  ``jax.eval_shape`` (the same abstract tracing XLA uses) from its
  recorded input avals and cross-checked against the recorded output
  Variables; graph surgery that desynchronizes them is caught before it
  becomes a wrong-numerics bug.
- PTA007 donation hazard  — a donated (``updated``) persistable is read
  after its last write. The Executor donates those buffers to XLA;
  the discipline "last write ends the buffer's life" must hold for
  donation to stay safe under any later scheduling change.
- PTA009 static-dim feed mismatch — a fed array disagrees with a declared
  static (non ``-1``) dim of its data Variable. Warns (host-side, with
  names) instead of letting XLA fail deep inside compilation.
"""
from __future__ import annotations

import functools
import warnings

import jax

from .diagnostics import DiagnosticReport, ERROR, WARNING
from .framework import AnalysisPass, PassContext, op_reads

__all__ = ["VerifierPass", "verify_program"]

_SINK_PREFIX = "_gsink"  # backward.py's dummy grad sinks: shape is a lie


class VerifierPass(AnalysisPass):
    name = "verifier"

    def __init__(self, infer_shapes=True):
        self.infer_shapes = infer_shapes

    # -- entry --------------------------------------------------------------
    def run(self, ctx: PassContext) -> None:
        self._check_structure(ctx)
        if self.infer_shapes:
            self._check_shapes(ctx)
        if ctx.feed_shapes is not None:
            self._check_feeds(ctx)

    # -- structural walk ----------------------------------------------------
    def _entry_defined(self, ctx):
        """Names with a value before op 0 executes: captured constants,
        feed/data slots, and persistables the Scope holds."""
        blk = ctx.block
        defined = set(ctx.program._constants)
        if ctx.feed_shapes is not None:
            # the replay env takes EVERY fed name, declared or not
            defined.update(ctx.feed_shapes)
        for name, v in blk.vars.items():
            if v.is_data:
                if ctx.feed_shapes is None:
                    defined.add(name)
            elif v.persistable:
                if ctx.scope_names is None or name in ctx.scope_names:
                    defined.add(name)
        return defined

    def _check_structure(self, ctx):
        blk, rep = ctx.block, ctx.report
        defined = self._entry_defined(ctx)
        # per-name write tracking: (writer_idx, writer_type, read_since)
        last_write: dict[str, tuple] = {}
        last_read: dict[str, int] = {}

        for idx, op in enumerate(ctx.ops):
            # reads first (an op reading and writing the same name — e.g.
            # grad_accumulate, the optimizer update — reads the OLD value)
            for n in op_reads(op):
                if n not in blk.vars and n not in ctx.program._constants \
                        and n not in (ctx.feed_shapes or ()):
                    rep.add("PTA002", ERROR,
                            f"input '{n}' is not declared in the block "
                            "(dangling reference — was the var created in "
                            "another Program?)",
                            op_idx=idx, op=op, var=n, pass_name=self.name)
                    continue
                if n not in defined and n not in last_write:
                    v = blk.vars.get(n)
                    hint = ""
                    if v is not None and v.is_data:
                        hint = " (declared as data but missing from feed)"
                    elif v is not None and v.persistable:
                        hint = (" (persistable not found in the Scope — "
                                "run the startup program first?)")
                    rep.add("PTA001", ERROR,
                            f"input '{n}' is read before any op defines "
                            f"it{hint}",
                            op_idx=idx, op=op, var=n, pass_name=self.name)
                last_read[n] = idx
                if n in last_write:
                    w_idx, w_type, _ = last_write[n]
                    last_write[n] = (w_idx, w_type, True)
            # writes
            seen_out = set()
            for n in op.output_names:
                if n in seen_out:
                    rep.add("PTA003", ERROR,
                            f"op writes output '{n}' twice; the replay env "
                            "keeps only the last value",
                            op_idx=idx, op=op, var=n, pass_name=self.name)
                seen_out.add(n)
                prev = last_write.get(n)
                if prev is not None and not prev[2]:
                    w_idx, w_type, _ = prev
                    if op.type == "assign_to":
                        rep.add("PTA004", ERROR,
                                f"assign_to clobbers '{n}' written by "
                                f"op#{w_idx} ({w_type}) that no op ever "
                                "read — the first computation is lost",
                                op_idx=idx, op=op, var=n,
                                pass_name=self.name)
                    else:
                        rep.add("PTA010", WARNING,
                                f"'{n}' written by op#{w_idx} ({w_type}) is "
                                "overwritten unread (dead write)",
                                op_idx=idx, op=op, var=n,
                                pass_name=self.name)
                last_write[n] = (idx, op.type, False)

        self._check_donation(ctx, last_write, last_read)

    def _check_donation(self, ctx, last_write, last_read):
        """Donated persistables: no read may follow the last write."""
        donated = ctx.donated
        if donated is None:
            # infer the Executor's donation set: SCOPE-HELD persistables
            # the program re-emits (Executor._compile donates exactly
            # persist_in ∩ written; a persistable the Scope doesn't hold
            # is plain env state and is never donated)
            donated = [n for n, v in ctx.block.vars.items()
                       if v.persistable and n in last_write
                       and (ctx.scope_names is None
                            or n in ctx.scope_names)]
        for n in donated:
            if n not in last_write:
                continue
            w_idx = last_write[n][0]
            r_idx = last_read.get(n, -1)
            if r_idx > w_idx:
                ctx.report.add(
                    "PTA007", ERROR,
                    f"donated persistable '{n}' is read at op#{r_idx} after "
                    f"its last write at op#{w_idx}; donation requires the "
                    "last write to end the buffer's live range",
                    op_idx=r_idx, op=ctx.ops[r_idx], var=n,
                    pass_name=self.name)

    # -- shape / dtype re-inference -----------------------------------------
    def _check_shapes(self, ctx):
        blk, rep = ctx.block, ctx.report
        amp = getattr(ctx.program, "_amp_cfg", None) is not None

        def recorded_aval(n):
            if n in ctx.program._constants:
                c = ctx.program._constants[n]
                return jax.ShapeDtypeStruct(tuple(c.shape), c.dtype)
            v = blk.vars.get(n)
            if v is None:
                return None
            return jax.ShapeDtypeStruct(tuple(v._data.shape), v._data.dtype)

        env: dict[str, jax.ShapeDtypeStruct] = {}
        for idx, op in enumerate(ctx.ops):
            specs = [env.get(n, recorded_aval(n)) if n is not None else None
                     for n in op.input_names]
            if any(s is None and n is not None
                   for s, n in zip(specs, op.input_names)):
                continue  # dangling input: already a PTA002 error
            try:
                out = jax.eval_shape(functools.partial(op.fn, **op.attrs),
                                     *specs)
            except Exception as e:  # noqa: BLE001 — any trace failure
                rep.add("PTA008", WARNING,
                        f"shape re-inference failed for op '{op.type}': "
                        f"{type(e).__name__}: {e}",
                        op_idx=idx, op=op, pass_name=self.name)
                for n in op.output_names:
                    r = recorded_aval(n)
                    if r is not None:
                        env[n] = r
                continue
            outs = out if isinstance(out, tuple) else (out,)
            for n, o in zip(op.output_names, outs):
                if o is None:  # optional output the kernel declined to fill
                    r = recorded_aval(n)
                    if r is not None:
                        env[n] = r
                    continue
                env[n] = jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                if n.startswith(_SINK_PREFIX):
                    continue  # placeholder vars, recorded shape is a stub
                r = recorded_aval(n)
                if r is None:
                    continue
                if tuple(r.shape) != tuple(o.shape):
                    rep.add("PTA005", ERROR,
                            f"shape drift on '{n}': recorded {tuple(r.shape)}"
                            f" but op '{op.type}' infers {tuple(o.shape)}",
                            op_idx=idx, op=op, var=n, pass_name=self.name)
                elif not amp and r.dtype != o.dtype:
                    rep.add("PTA006", ERROR,
                            f"dtype drift on '{n}': recorded {r.dtype} but "
                            f"op '{op.type}' infers {o.dtype}",
                            op_idx=idx, op=op, var=n, pass_name=self.name)

    # -- feed cross-check ---------------------------------------------------
    def _check_feeds(self, ctx):
        for name, (shape, _dtype) in (ctx.feed_shapes or {}).items():
            v = ctx.block.vars.get(name)
            if v is None or not v.is_data:
                continue
            dyn = set(getattr(v, "dynamic_dims", ()) or ())
            declared = tuple(v._data.shape)
            mismatch = None
            if len(shape) != len(declared):
                mismatch = (f"rank {len(shape)} vs declared rank "
                            f"{len(declared)}")
            else:
                bad = [i for i in range(len(declared))
                       if i not in dyn and declared[i] != shape[i]]
                if bad:
                    mismatch = (f"dims {bad} of fed shape {tuple(shape)} != "
                                f"declared {declared} (dims {sorted(dyn)} "
                                "are dynamic)")
            if mismatch:
                msg = (f"feed '{name}' mismatches the declared static shape: "
                       f"{mismatch}; the program will be re-traced with the "
                       "fed shape, but a declared static dim usually means "
                       "this is a bug at the call site")
                ctx.report.add("PTA009", WARNING, msg, var=name,
                               pass_name=self.name)
                warnings.warn(msg, RuntimeWarning, stacklevel=4)


def verify_program(program, ops=None, fetch_names=(), feed_shapes=None,
                   donated=None, scope_names=None, infer_shapes=True,
                   raise_on_error=True):
    """Run the verifier over ``program`` and return the DiagnosticReport.

    ``infer_shapes=False`` limits it to the structural checks (used at
    graph-construction sites like append_backward, where re-tracing every
    op would double build time; the Executor always runs the full check
    before compiling).
    """
    ctx = PassContext(program, ops=ops, fetch_names=fetch_names,
                      feed_shapes=feed_shapes, donated=donated,
                      scope_names=scope_names)
    VerifierPass(infer_shapes=infer_shapes).run(ctx)
    if raise_on_error:
        ctx.report.raise_if_errors()
    return ctx.report
