"""Pass framework: PassContext / Pass / PassManager.

TPU-native analog of the reference's ``paddle/fluid/framework/ir``
pass machinery (``pass.h`` Pass::Apply over an ir::Graph, registered and
sequenced by ``PassBuilder``): here the "graph" is the recorded
``Block.ops`` list itself — ops are already in SSA-ish program order and
name-linked, so passes are plain list-to-list rewrites instead of
pointer-graph surgery.

Two pass families:

- ``AnalysisPass``  — read-only; emits Diagnostics into the report
  (verifier, lint).
- ``RewritePass``   — returns a NEW op list (the input Program is never
  mutated: the Executor compiles the rewritten list, while the user's
  Program object — and its cache-keying version — stays untouched).

``PassManager.run`` threads one PassContext through the sequence and
records per-pass op-count deltas in ``report.pass_stats`` (the reference
logs the same thing per ir pass with VLOG).
"""
from __future__ import annotations

import logging
import time

from ..obs import metrics as _obs_metrics
from .diagnostics import DiagnosticReport

_log = logging.getLogger("paddle_tpu.analysis")

__all__ = ["PassContext", "Pass", "AnalysisPass", "RewritePass",
           "PassManager", "op_reads", "op_writes", "normalize_fetch"]


def normalize_fetch(fetch_list):
    """One canonical fetch_list resolution: ``(names, variable_handles)``.
    Every consumer (Executor key, verifier/DCE roots, lint) must agree on
    these names or the pass roots silently diverge from the replay."""
    from ..static_.program import Variable

    names = tuple(f.name if isinstance(f, Variable) else str(f)
                  for f in fetch_list)
    handles = tuple(f for f in fetch_list if isinstance(f, Variable))
    return names, handles


def op_reads(op):
    """Input names an op actually reads (None slots are absent optionals)."""
    return [n for n in op.input_names if n is not None]


def op_writes(op):
    return list(op.output_names)


class PassContext:
    """Everything a pass may consult, bundled (ref: ir pass attrs).

    - ``program``      — the Program under analysis (never mutated)
    - ``ops``          — current working op list (rewrites replace it)
    - ``fetch_names``  — names the caller will fetch (DCE roots)
    - ``feed_shapes``  — {name: (shape, dtype)} of the actual feeds, when
                         known (Executor._compile knows; CLI may not)
    - ``donated``      — names whose buffers the Executor donates, when known
    - ``scope_names``  — persistable names the Scope actually holds, when
                         known (None = assume every persistable is backed)
    - ``report``       — DiagnosticReport collecting findings
    """

    def __init__(self, program, ops=None, fetch_names=(), feed_shapes=None,
                 donated=None, scope_names=None, fetch_vars=(), report=None):
        self.program = program
        self.ops = list(ops if ops is not None else program.global_block.ops)
        self.fetch_names = tuple(fetch_names)
        self.feed_shapes = feed_shapes
        self.donated = donated
        self.scope_names = scope_names
        self.fetch_vars = tuple(fetch_vars)  # Variable handles, when known
        self.report = report if report is not None else \
            DiagnosticReport(program)

    @property
    def block(self):
        return self.program.global_block

    def protected_names(self):
        """Names whose final value is observable outside the replay:
        fetches, persistables (restored into the Scope), feed/data slots.
        Rewrites must keep every write to these."""
        blk = self.block
        out = set(self.fetch_names)
        for name, v in blk.vars.items():
            if v.persistable or v.is_data:
                out.add(name)
        return out


class Pass:
    """Base pass (ref: ir/pass.h). ``name`` keys pass_stats and diagnostic
    provenance."""

    name = "pass"

    def run(self, ctx: PassContext) -> None:
        raise NotImplementedError


class AnalysisPass(Pass):
    """Read-only pass: inspects ctx.ops / ctx.program, emits diagnostics."""


class RewritePass(Pass):
    """Op-list rewrite: ``rewrite`` returns the new list; the manager
    records the op-count delta under this pass's name."""

    def run(self, ctx: PassContext) -> None:
        before = len(ctx.ops)
        ctx.ops = self.rewrite(ctx)
        removed = before - len(ctx.ops)
        ctx.report.pass_stats[self.name] = {
            "ops_before": before, "ops_after": len(ctx.ops),
            "removed": removed}
        if removed:
            _log.info("pass %s: removed %d of %d ops", self.name, removed,
                      before)

    def rewrite(self, ctx: PassContext) -> list:
        raise NotImplementedError


class PassManager:
    """Sequences passes over one PassContext (ref: ir PassBuilder +
    inference/analysis Analyzer::RunAnalysis)."""

    def __init__(self, passes=()):
        self.passes = list(passes)

    def add(self, p):
        self.passes.append(p)
        return self

    def run(self, program, ops=None, fetch_names=(), feed_shapes=None,
            donated=None, scope_names=None, fetch_vars=(), report=None):
        ctx = PassContext(program, ops=ops, fetch_names=fetch_names,
                          feed_shapes=feed_shapes, donated=donated,
                          scope_names=scope_names, fetch_vars=fetch_vars,
                          report=report)
        return self.run_ctx(ctx)

    def run_ctx(self, ctx):
        for p in self.passes:
            t0 = time.perf_counter()
            p.run(ctx)
            ms = (time.perf_counter() - t0) * 1e3
            # per-pass compile-time attribution: obs.metrics aggregates
            # every pass process-wide for tools/obs_report.py; the
            # report's pass_stats stays rewrite-only (an always-on
            # verifier entry would break its "no rewrites ran" == {}
            # contract), so ms joins entries a rewrite already made
            if p.name in ctx.report.pass_stats:
                ctx.report.pass_stats[p.name]["ms"] = ms
            _obs_metrics.histogram(f"analysis.pass.{p.name}.ms").observe(ms)
        return ctx
