"""Static peak-memory estimate: a per-op liveness walk over the Program.

TPU-native analog of the reference's ``framework/ir`` memory-optimize
passes (``memory_optimize_pass.cc`` / ``inplace_op_pass.cc`` reuse
buffers from exactly this walk) with the accounting turned outward: the
number a *planner* needs is the executable's high-water HBM mark, so the
walk mirrors XLA buffer assignment's charging rules instead of rewriting
the graph —

- **entry buffers** (feeds, scope-held persistables, captured
  constants) are resident for the whole call: XLA allocates arguments
  up front. Donated persistables alias their outputs, so re-emitted
  parameters count ONCE (the ``alias_size`` convention
  ``memory_analysis()`` reports).
- **outputs** (fetches) are distinct allocations.
- **temps** (everything else) overlap by liveness: during op ``i`` its
  inputs and outputs coexist, so the per-op charge is the sum of every
  temp version whose ``[def, last_use]`` interval covers ``i``
  (``analysis.dataflow`` provides the versioned intervals).
- **convolution workspace**: conv ops lower through an im2col-style
  patch matrix (CPU and TPU backends both materialize scratch of that
  order), charged transiently during the conv op —
  ``B * out_spatial * (Cin/groups * prod(k)) * itemsize``. Without it
  the estimate undershoots conv nets by ~2x; with it the zoo models
  land within the 15% acceptance band of ``memory_analysis()``.
- **fused windows** (``steps=K``): feeds and fetches stack K copies
  (the executable's real argument/output shapes); the temp peak is
  per-iteration (the scan body reuses its buffers each step).

``estimate_entry`` is what ``Executor._build`` attaches per compiled
entry (validated against ``memory_analysis()`` by the journal's
``memory`` event and gated in ``tools/run_report.py --diff``);
``candidate_peak`` is the cheap per-candidate form ``fleet.planner``
prices layouts with; ``remat_candidates`` scores long-lived,
cheap-to-recompute activations for ROADMAP item 2's recompute
decisions (PTL104 hints).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from . import dataflow as _dataflow
from .diagnostics import DiagnosticReport, WARNING
from .framework import normalize_fetch

__all__ = [
    "MemoryEstimate", "estimate_entry", "candidate_peak",
    "remat_candidates", "memory_report", "measured_peak_bytes",
    "CHEAP_RECOMPUTE",
]

# op types cheap enough to replay instead of keeping resident: one
# pass over the operand, no contraction — the classic remat set (the
# planner's recompute decisions start here)
CHEAP_RECOMPUTE = frozenset((
    "relu", "gelu", "tanh", "sigmoid", "silu", "swish", "leaky_relu",
    "elu", "softplus", "hardswish", "hardsigmoid", "dropout",
    "dropout_axes", "alpha_dropout", "scale", "cast", "abs", "square",
    "exp", "add", "subtract", "multiply", "elementwise_add",
    "elementwise_mul", "elementwise_sub", "reshape", "flatten",
    "transpose", "concat", "split",
))

_CONV_OPS = ("conv2d", "depthwise_conv2d", "conv3d", "conv2d_transpose")


def _conv_workspace(op, shape_of, itemsize=4):
    """Transient im2col patch bytes for one conv(-grad) op; 0 for
    everything else. The patch matrix is
    ``B x out_spatial x (Cin/groups * prod(kernel))`` — the reference
    shape both the XLA:CPU im2col lowering and the TPU's implicit
    patch loads materialize. Layout-aware: the out-channel dim sits at
    ``ref[1]`` (NCHW-family) or ``ref[-1]`` (channel-last), read from
    the op's ``data_format`` attr; both counts derive from element
    totals so the weight layout (OIHW vs HWIO) never matters. Grad ops
    carry no attrs and default to channel-first — the recorded
    convention of every model in the zoo."""
    base = op.type[:-5] if op.type.endswith("@grad") else op.type
    if base not in _CONV_OPS:
        return 0
    names = [n for n in op.input_names if n is not None]
    if len(names) < 2:
        return 0
    w = shape_of(names[1])
    if w is None or len(w) < 4:
        return 0
    if op.type.endswith("@grad"):
        # dW's im2col runs over the FORWARD output spatial extent,
        # which is the incoming grad's shape (input slot 2)
        ref = shape_of(names[2]) if len(names) > 2 else None
    else:
        ref = shape_of(op.output_names[0])
    if ref is None or len(ref) < 4:
        return 0
    channel_last = str(op.attrs.get("data_format", "NCHW"))\
        .endswith("C")
    cout = int(ref[-1] if channel_last else ref[1])
    if cout <= 0:
        return 0
    batch, out_numel, w_numel = int(ref[0]), 1, 1
    for s in ref[1:]:
        out_numel *= int(s)
    for s in w:
        w_numel *= int(s)
    spatial = out_numel // cout          # prod of the spatial dims
    patch = w_numel // cout              # Cin/groups * prod(kernel)
    return batch * spatial * patch * itemsize


@dataclasses.dataclass
class MemoryEstimate:
    """Predicted high-water HBM for one compiled entry.

    ``peak_bytes = arg_bytes + const_bytes + output_bytes +
    temp_peak_bytes`` — directly comparable to ``memory_analysis()``'s
    ``argument + output + temp - alias`` (see
    ``measured_peak_bytes``). ``per_device_bytes`` divides each class
    by its shard factor under the entry's plan / data mesh."""

    peak_bytes: int
    per_device_bytes: int
    arg_bytes: int
    const_bytes: int
    output_bytes: int
    temp_peak_bytes: int
    peak_op: tuple | None        # (op index, op type) of the temp peak
    steps: int | None            # fused-window K (None = single step)
    timeline: list               # per-op temp+workspace bytes
    liveness: _dataflow.Liveness

    def as_event(self):
        """JSON-safe payload for the journal's ``memory`` event."""
        return {
            "peak_bytes": int(self.peak_bytes),
            "per_device_bytes": int(self.per_device_bytes),
            "arg_bytes": int(self.arg_bytes),
            "const_bytes": int(self.const_bytes),
            "output_bytes": int(self.output_bytes),
            "temp_peak_bytes": int(self.temp_peak_bytes),
            "peak_op": (list(self.peak_op)
                        if self.peak_op is not None else None),
            "steps": self.steps,
        }


def _temp_walk(program, ops, liveness, feed_shapes=None):
    """Per-op live temp bytes (+ conv workspace): the overlap part of
    the estimate. Returns (peak, peak_op, timeline)."""
    temps = liveness.temps()
    n_ops = liveness.n_ops
    add_at, drop_after = {}, {}
    for l in temps:
        i = max(l.def_idx, 0)
        add_at[i] = add_at.get(i, 0) + l.nbytes
        drop_after[min(l.last_use, n_ops - 1)] = \
            drop_after.get(min(l.last_use, n_ops - 1), 0) + l.nbytes

    def shape_of(name):
        if feed_shapes and name in feed_shapes:
            return tuple(feed_shapes[name][0])
        if name in program._constants:
            return tuple(program._constants[name].shape)
        v = program.global_block.vars.get(name)
        return tuple(v._data.shape) if v is not None else None

    live = 0
    peak, peak_op = 0, None
    timeline = []
    for i, op in enumerate(ops):
        live += add_at.get(i, 0)
        here = live + _conv_workspace(op, shape_of)
        timeline.append(here)
        if here > peak:
            peak, peak_op = here, (i, op.type)
        live -= drop_after.get(i, 0)
    return peak, peak_op, timeline


def _shard_factor(spec, axes):
    n = 1
    for part in tuple(spec or ()):
        for ax in (part if isinstance(part, tuple) else (part,)):
            if ax is not None:
                n *= int(axes.get(ax, 1))
    return max(1, n)


def estimate_entry(program, ops=None, fetch_list=(), feed_shapes=None,
                   scope_names=None, steps=None, plan=None,
                   data_devices=1):
    """Predict one compiled entry's peak HBM bytes (see module
    docstring). ``feed_shapes`` is the Executor's ``{name: (shape,
    dtype)}`` of the ACTUAL feeds; ``plan`` (a fleet ShardingPlan) or
    ``data_devices`` (plain one-axis DP) select the per-device
    division."""
    fetch_names, _ = normalize_fetch(fetch_list)
    blk = program.global_block
    ops = list(ops if ops is not None else blk.ops)
    liveness = _dataflow.analyze(
        program, ops=ops, fetch_names=fetch_names,
        feed_shapes=feed_shapes, scope_names=scope_names, steps=steps)
    k = int(steps) if steps else 1

    def nbytes(name):
        return _dataflow._var_nbytes(program, name, feed_shapes)

    entry = [l for l in liveness.lives if l.def_idx == _dataflow.ENTRY]
    feed_b = sum(l.nbytes for l in entry if l.kind == "feed") * k
    persist_b = sum(l.nbytes for l in entry if l.kind == "persistable")
    const_b = sum(l.nbytes for l in entry if l.kind == "constant")
    out_b = sum(nbytes(n) for n in fetch_names) * k
    temp_peak, peak_op, timeline = _temp_walk(program, ops, liveness,
                                              feed_shapes)

    # per-device division: each class by its own shard factor
    if plan is not None:
        axes = dict(plan.axes)
        d = int(axes.get("data", 1))
        feed_pd = sum(
            l.nbytes // _shard_factor(
                plan.feed_spec_for(
                    l.name, (feed_shapes or {}).get(l.name, (None,))[0]),
                axes)
            for l in entry if l.kind == "feed") * k
        persist_pd = 0
        for l in entry:
            if l.kind != "persistable":
                continue
            v = blk.vars.get(l.name)
            shape = tuple(v._data.shape) if v is not None else None
            persist_pd += l.nbytes // _shard_factor(
                plan.spec_for(l.name, shape), axes)
    else:
        d = max(1, int(data_devices))
        feed_pd = 0
        for l in entry:
            if l.kind != "feed":
                continue
            shape = (feed_shapes or {}).get(l.name, ((),))[0] or ()
            divisible = (d > 1 and len(shape) >= 1 and shape[0] > 0
                         and shape[0] % d == 0)
            feed_pd += (l.nbytes // d) if divisible else l.nbytes
        feed_pd *= k
        persist_pd = persist_b  # plain DP replicates persistables
    per_device = persist_pd + feed_pd + const_b + out_b + temp_peak // d

    return MemoryEstimate(
        peak_bytes=persist_b + feed_b + const_b + out_b + temp_peak,
        per_device_bytes=per_device,
        arg_bytes=persist_b + feed_b, const_bytes=const_b,
        output_bytes=out_b, temp_peak_bytes=temp_peak,
        peak_op=peak_op, steps=steps, timeline=timeline,
        liveness=liveness)


def candidate_peak(program, ops=None):
    """The planner's one-walk profile: ``(act_peak_bytes,
    const_bytes)`` of a Program, candidate-independent. Per-candidate
    per-device peaks combine these with the layout's own per-feed and
    per-param shard factors (which need per-name granularity the
    planner computes from its ProgramFacts)."""
    est = estimate_entry(program, ops=ops)
    return est.temp_peak_bytes, est.const_bytes


def remat_candidates(program, ops=None, fetch_list=(), feed_shapes=None,
                     min_bytes=4096, min_span=None, liveness=None):
    """Rematerialization candidates: temp versions that are (a) big
    (``>= min_bytes``), (b) long-lived (live across ``>= min_span``
    ops — default an eighth of the program), and (c) produced by a
    cheap op (``CHEAP_RECOMPUTE``): dropping the buffer and replaying
    the producer trades one cheap op for ``nbytes`` of high-water HBM
    across the span. Scored ``nbytes * span / n_ops`` (bytes weighted
    by the fraction of the program they squat), best first.
    ``liveness`` reuses an existing walk (``MemoryEstimate.liveness``)
    instead of re-analyzing."""
    if liveness is None:
        fetch_names, _ = normalize_fetch(fetch_list)
        ops = list(ops if ops is not None
                   else program.global_block.ops)
        liveness = _dataflow.analyze(program, ops=ops,
                                     fetch_names=fetch_names,
                                     feed_shapes=feed_shapes)
    n_ops = max(1, liveness.n_ops)
    if min_span is None:
        min_span = max(4, n_ops // 8)
    out = []
    for l in liveness.temps():
        if l.writer not in CHEAP_RECOMPUTE or l.nbytes < min_bytes \
                or l.span < min_span:
            continue
        out.append({
            "name": l.name, "writer": l.writer, "bytes": l.nbytes,
            "def": l.def_idx, "last_use": l.last_use, "span": l.span,
            "score": l.nbytes * l.span / n_ops,
        })
    out.sort(key=lambda c: -c["score"])
    return out


def memory_report(program, ops=None, fetch_list=(), feed_shapes=None,
                  steps=None, plan=None, data_devices=1,
                  min_bytes=4096, min_span=None):
    """The memory analysis as a diagnosable unit: returns
    ``(MemoryEstimate, DiagnosticReport)`` with one PTL104 hint per
    remat candidate — what ``tools/lint_program.py --memory`` prints
    and tests assert codes against."""
    est = estimate_entry(program, ops=ops, fetch_list=fetch_list,
                         feed_shapes=feed_shapes, steps=steps,
                         plan=plan, data_devices=data_devices)
    report = DiagnosticReport(program)
    for c in remat_candidates(program, min_bytes=min_bytes,
                              min_span=min_span,
                              liveness=est.liveness):
        report.add(
            "PTL104", WARNING,
            f"'{c['name']}' ({c['bytes']} B from cheap op "
            f"'{c['writer']}') stays live across {c['span']} ops "
            f"(op#{c['def']} -> op#{c['last_use']}): a "
            "rematerialization candidate — recomputing it at last use "
            "would cut the high-water mark",
            op_idx=c["def"], var=c["name"], pass_name="memory")
    return est, report


def measured_peak_bytes(mem):
    """The comparable number from ``memory_analysis()``'s dict (the
    ``obs.mfu.entry_analysis`` ``memory`` field): ``argument + output
    + temp - alias`` — donated buffers count once, matching
    ``MemoryEstimate.peak_bytes``'s convention. None when the backend
    reported nothing."""
    if not mem:
        return None
    total = (mem.get("argument_size", 0) + mem.get("output_size", 0)
             + mem.get("temp_size", 0) - mem.get("alias_size", 0))
    return int(total) if total > 0 else None
