"""Static concurrency lint: lock order, blocking-under-lock, shared state.

The Program-IR side of ``paddle_tpu.analysis`` checks graphs; this
module checks the *host runtime's own Python source* — the threaded
side (router/pool reader threads, serve engine step lock, DataLoader
workers, async checkpoint writer) where the PR-14/15 review cycle
burned a full round on exactly three bug shapes. It is an AST lint
(no imports, no execution: linting a file can never deadlock), the
static half of the ``obs.lockdep`` runtime validator:

==========  =========  =====================================================
code        severity   meaning
==========  =========  =====================================================
PTC001      error      inconsistent lock-acquisition order: two lock
                       classes are taken A-then-B on one path and
                       B-then-A on another — the deadlock precondition
PTC002      error      blocking call under a held lock (``time.sleep``,
                       ``Thread.join``, ``Popen.wait``/``communicate``,
                       ``urlopen``/HTTP scrape, untimed ``queue.get``):
                       the PR-15 router-stall class
PTC003      warning    attribute written both from a spawned-thread
                       target and from a public method with no shared
                       lock in scope on at least one side
==========  =========  =====================================================

The model: per module, every lock *token* is either ``self.<attr>``
where ``<attr>`` was assigned a ``threading.Lock()``/``RLock()``/
``Condition()`` (or an ``obs.lockdep`` factory) anywhere in the class
— giving the token ``ClassName.<attr>`` — or a module-level name bound
the same way. Each function is walked in statement order with the held
set live (``with`` blocks scope it exactly; bare ``acquire()`` holds
until a matching ``release()`` or function end), recording acquisition
pairs, blocking calls under a non-empty held set, and (one level deep)
locks acquired by ``self.method()`` calls made while holding.

Deliberate non-goals that bound the false-positive rate: same-token
nesting is not an ordering edge, ``cond.wait()`` on the HELD lock
token is legal (it releases while waiting — that is what Conditions
are for), ``"sep".join(x)`` / ``dict.get(k)`` are not ``Thread.join``
/ ``queue.get`` (arity + receiver heuristics below), and a finding on
a line whose comment carries ``lockdep: waive`` or ``noqa: PTC00x``
is reported but ``waived`` — the CLI gate counts only unwaived
PTC001/PTC002.

Lock-ordering contract this lint (and the runtime validator) enforce
in-tree: **router → pool → replica** on the fleet control plane and
**engine.step → scheduler → cache** inside a replica; the journal and
metrics locks are leaves (nothing may be acquired under them).
"""
from __future__ import annotations

import ast
import os

__all__ = ["Finding", "lint_source", "lint_file", "lint_tree",
           "gate_findings", "BLOCKING_NAMES"]

# direct-call names that block (module function style: time.sleep,
# urllib.request.urlopen, subprocess.check_output / run)
BLOCKING_NAMES = ("sleep", "urlopen", "check_output", "check_call")
# attribute-call names that block, with disambiguation handled in
# _blocking_reason: wait/communicate (Popen/Event), join (Thread — not
# str.join), get (queue — not dict.get)
_BLOCKING_ATTRS = ("sleep", "urlopen", "check_output", "check_call",
                   "wait", "communicate", "join", "get")

_WAIVE_MARKERS = ("lockdep: waive", "lockdep:waive")

_LOCK_FACTORY_SUFFIXES = ("Lock", "RLock", "Condition", "Semaphore",
                          "BoundedSemaphore")


class Finding:
    """One lint finding with source provenance."""

    __slots__ = ("code", "severity", "message", "file", "line", "cls",
                 "func", "locks", "waived")

    def __init__(self, code, severity, message, file, line, cls=None,
                 func=None, locks=(), waived=False):
        self.code = code
        self.severity = severity
        self.message = message
        self.file = file
        self.line = line
        self.cls = cls
        self.func = func
        self.locks = tuple(locks)
        self.waived = bool(waived)

    def as_dict(self):
        return {"code": self.code, "severity": self.severity,
                "message": self.message, "file": self.file,
                "line": self.line, "class": self.cls, "func": self.func,
                "locks": list(self.locks), "waived": self.waived}

    def __repr__(self):
        w = " (waived)" if self.waived else ""
        where = f"{self.file}:{self.line}"
        ctx = ".".join(x for x in (self.cls, self.func) if x)
        return f"[{self.code}]{w} {where} {ctx}: {self.message}"


def _is_lock_factory(call):
    """Does this Call construct a lock? ``threading.Lock()``,
    ``Lock()``, ``lockdep.lock("x")`` / ``.rlock("x")`` all count."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("lock", "rlock") and isinstance(fn.value, ast.Name) \
                and "lockdep" in fn.value.id:
            return True
        return fn.attr.endswith(_LOCK_FACTORY_SUFFIXES)
    if isinstance(fn, ast.Name):
        if fn.id in ("lock", "rlock"):
            return False  # bare helpers: too ambiguous without import info
        return fn.id.endswith(_LOCK_FACTORY_SUFFIXES)
    return False


def _dotted(node):
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ClassModel:
    def __init__(self, name):
        self.name = name
        self.lock_attrs = set()      # attr names holding locks
        self.thread_targets = set()  # method names used as Thread targets
        self.methods = {}            # name -> _FuncModel


class _FuncModel:
    def __init__(self, name, node, cls=None):
        self.name = name
        self.node = node
        self.cls = cls
        self.pairs = []        # (held_token, acquired_token, line)
        self.first_locks = []  # (token, line) acquired with nothing held
        self.blocking = []     # (line, what, held_tokens)
        self.calls_holding = []  # (method_name, held_tokens, line)
        self.writes = []       # (attr, line, held_tokens)


def _collect_locks(tree):
    """First pass: module-level lock names + per-class lock attrs +
    thread-target methods."""
    module_locks = set()
    classes = {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if isinstance(value, ast.Call) and _is_lock_factory(value):
                for t in targets:
                    if isinstance(t, ast.Name):
                        module_locks.add(t.id)
        elif isinstance(node, ast.ClassDef):
            cm = classes.setdefault(node.name, _ClassModel(node.name))
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call) and \
                        _is_lock_factory(sub.value):
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            cm.lock_attrs.add(t.attr)
                if isinstance(sub, ast.Call):
                    fd = _dotted(sub.func) or ""
                    if fd.endswith("Thread"):
                        for kw in sub.keywords:
                            if kw.arg == "target" and \
                                    isinstance(kw.value, ast.Attribute) \
                                    and isinstance(kw.value.value,
                                                   ast.Name) \
                                    and kw.value.value.id == "self":
                                cm.thread_targets.add(kw.value.attr)
    return module_locks, classes


def _lock_token(node, cls_model, module_locks):
    """Resolve an expression to a lock token, or None. ``self._lock``
    -> ``Cls._lock``; a module-level lock name -> that name."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self" \
            and cls_model is not None and node.attr in cls_model.lock_attrs:
        return f"{cls_model.name}.{node.attr}"
    if isinstance(node, ast.Name) and node.id in module_locks:
        return node.id
    return None


def _blocking_reason(call, held, cls_model, module_locks):
    """Name the blocking operation in ``call``, or None if benign."""
    fn = call.func
    has_timeout = any(kw.arg in ("timeout", "timeout_s") and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in call.keywords)
    nonblocking = any(
        kw.arg == "block" and isinstance(kw.value, ast.Constant)
        and kw.value.value is False for kw in call.keywords)
    if isinstance(fn, ast.Name):
        if fn.id in BLOCKING_NAMES:
            return fn.id
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    name = fn.attr
    if name not in _BLOCKING_ATTRS:
        return None
    recv = fn.value
    recv_dotted = _dotted(recv) or ""
    if name == "sleep":
        return "time.sleep" if recv_dotted in ("time", "_time") \
            else f"{recv_dotted}.sleep"
    if name in ("urlopen", "check_output", "check_call"):
        return f"{recv_dotted}.{name}"
    if name == "communicate":
        return f"{recv_dotted}.communicate"
    if name == "wait":
        # cond.wait() on the HELD lock is the condition-variable
        # pattern (it releases while waiting) — only flag waits on
        # something NOT currently held, and only untimed ones
        tok = _lock_token(recv, cls_model, module_locks)
        if tok is not None and tok in held:
            return None
        if has_timeout or (call.args and not (
                isinstance(call.args[0], ast.Constant)
                and call.args[0].value is None)):
            return None  # bounded wait: a stall, not a deadlock arm
        if isinstance(recv, ast.Constant):
            return None
        return f"{recv_dotted or '<expr>'}.wait"
    if name == "join":
        # str.join takes exactly one positional (the iterable);
        # Thread/Process.join takes zero positionals (+ optional
        # timeout kwarg). ''.join(...) and os.path.join(...) are the
        # common benign shapes — require zero positionals.
        if call.args:
            return None
        if isinstance(recv, ast.Constant):
            return None
        if has_timeout:
            return None
        return f"{recv_dotted or '<expr>'}.join"
    if name == "get":
        # dict.get(k[, d]) carries positionals; queue.get() blocks with
        # none. Require a queue-ish receiver name to keep arbitrary
        # zero-arg .get() wrappers out.
        if call.args or nonblocking or has_timeout:
            return None
        leaf = recv_dotted.rsplit(".", 1)[-1].lower()
        if leaf in ("q", "queue") or leaf.endswith(("_q", "_queue",
                                                    "queue")):
            return f"{recv_dotted}.get (untimed)"
        return None
    return None


class _FuncWalker:
    """Walks one function's statements in order, tracking the held-lock
    list (a stack of tokens)."""

    def __init__(self, model, cls_model, module_locks):
        self.m = model
        self.cls = cls_model
        self.module_locks = module_locks
        self.held = []

    def walk(self):
        self._body(self.m.node.body)

    # -- statement dispatch --------------------------------------------------
    def _body(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in st.items:
                self._expr(item.context_expr)
                tok = _lock_token(item.context_expr, self.cls,
                                  self.module_locks)
                if tok is not None:
                    self._acquire(tok, item.context_expr.lineno)
                    self.held.append(tok)
                    pushed += 1
            self._body(st.body)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs run later, under their own discipline
        if isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            for f in ast.iter_fields(st):
                pass
            self._expr(getattr(st, "test", None) or
                       getattr(st, "iter", None))
            self._body(st.body)
            self._body(st.orelse)
            return
        if isinstance(st, ast.Try):
            self._body(st.body)
            for h in st.handlers:
                self._body(h.body)
            self._body(st.orelse)
            self._body(st.finalbody)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    self.m.writes.append((t.attr, st.lineno,
                                          tuple(self.held)))
            self._expr(getattr(st, "value", None))
            return
        # generic statement: scan contained expressions
        for child in ast.iter_child_nodes(st):
            self._expr(child)

    # -- expression scan (calls + acquire/release) ---------------------------
    def _expr(self, node):
        if node is None:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("acquire", "release"):
                tok = _lock_token(fn.value, self.cls, self.module_locks)
                if tok is not None:
                    if fn.attr == "acquire":
                        self._acquire(tok, sub.lineno)
                        self.held.append(tok)
                    elif tok in self.held:
                        self.held.remove(tok)
                    continue
            what = _blocking_reason(sub, self.held, self.cls,
                                    self.module_locks)
            if what is not None and self.held:
                self.m.blocking.append((sub.lineno, what,
                                        tuple(self.held)))
            if isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id == "self" and self.held:
                self.m.calls_holding.append((fn.attr, tuple(self.held),
                                             sub.lineno))

    def _acquire(self, tok, line):
        if not self.held:
            self.m.first_locks.append((tok, line))
        for h in self.held:
            if h != tok:
                self.m.pairs.append((h, tok, line))


def _analyze_module(tree, filename):
    module_locks, classes = _collect_locks(tree)
    funcs = []

    def visit_func(node, cls_model):
        fm = _FuncModel(node.name, node, cls=cls_model.name
                        if cls_model else None)
        if cls_model is not None:
            cls_model.methods[node.name] = fm
        _FuncWalker(fm, cls_model, module_locks).walk()
        funcs.append(fm)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_func(node, None)
        elif isinstance(node, ast.ClassDef):
            cm = classes[node.name]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    visit_func(sub, cm)
    return module_locks, classes, funcs


def _expand_call_pairs(classes, funcs):
    """One-level interprocedural expansion: ``self.m()`` made while
    holding L contributes (L, first-lock-of-m) ordering pairs."""
    out = []
    for fm in funcs:
        if fm.cls is None:
            continue
        cm = classes.get(fm.cls)
        if cm is None:
            continue
        for name, held, line in fm.calls_holding:
            callee = cm.methods.get(name)
            if callee is None:
                continue
            for tok, _ in callee.first_locks:
                for h in held:
                    if h != tok:
                        out.append((h, tok, line, fm, callee))
    return out


def lint_source(src, filename="<string>"):
    """Lint one module's source text; returns a list of Findings."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Finding("PTC000", "warning",
                        f"unparseable: {e}", filename,
                        getattr(e, "lineno", 0) or 0)]
    lines = src.splitlines()

    def waived(line_no, code):
        idx = line_no - 1
        if not 0 <= idx < len(lines):
            return False
        text = lines[idx]
        if "#" not in text:
            return False
        comment = text[text.index("#"):].lower()
        if any(m in comment for m in _WAIVE_MARKERS):
            return True
        return "noqa" in comment and code.lower() in comment

    module_locks, classes, funcs = _analyze_module(tree, filename)
    findings = []

    # PTC002: blocking call under a held lock
    for fm in funcs:
        for line, what, held in fm.blocking:
            findings.append(Finding(
                "PTC002", "error",
                f"blocking call {what} while holding "
                f"{', '.join(held)} — move it outside the critical "
                "section (or bound it with a timeout)",
                filename, line, cls=fm.cls, func=fm.name, locks=held,
                waived=waived(line, "PTC002")))

    # PTC001: inconsistent acquisition order across the module
    pair_sites = {}   # (a, b) -> (line, func-label)
    for fm in funcs:
        label = ".".join(x for x in (fm.cls, fm.name) if x)
        for a, b, line in fm.pairs:
            pair_sites.setdefault((a, b), (line, label))
    for a, b, line, fm, callee in _expand_call_pairs(classes, funcs):
        label = (".".join(x for x in (fm.cls, fm.name) if x)
                 + f" -> {callee.name}()")
        pair_sites.setdefault((a, b), (line, label))
    reported = set()
    for (a, b), (line, label) in sorted(pair_sites.items(),
                                        key=lambda kv: kv[1][0]):
        if (b, a) not in pair_sites or frozenset((a, b)) in reported:
            continue
        reported.add(frozenset((a, b)))
        rline, rlabel = pair_sites[(b, a)]
        findings.append(Finding(
            "PTC001", "error",
            f"inconsistent lock order: {a} -> {b} here but "
            f"{b} -> {a} at line {rline} ({rlabel}) — pick one order "
            "and document it",
            filename, line, func=label, locks=(a, b),
            waived=waived(line, "PTC001") or waived(rline, "PTC001")))

    # PTC003: attr written from a thread target AND a public method,
    # with an unguarded write on at least one side
    for cm in classes.values():
        if not cm.thread_targets:
            continue
        entry = set(cm.thread_targets)
        # one level of self-call closure from the thread entries
        for name in list(entry):
            fm = cm.methods.get(name)
            if fm is not None:
                entry.update(n for n, _, _ in fm.calls_holding
                             if n in cm.methods)
                for sub in ast.walk(fm.node):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.func.value.id == "self" and \
                            sub.func.attr in cm.methods:
                        entry.add(sub.func.attr)
        thread_writes = {}   # attr -> (line, guarded?)
        public_writes = {}
        for name, fm in cm.methods.items():
            for attr, line, held in fm.writes:
                if attr in cm.lock_attrs:
                    continue
                rec = (line, bool(held))
                if name in entry:
                    thread_writes.setdefault(attr, rec)
                elif not name.startswith("_"):
                    public_writes.setdefault(attr, rec)
        for attr in sorted(set(thread_writes) & set(public_writes)):
            tl, tg = thread_writes[attr]
            pl, pg = public_writes[attr]
            if tg and pg:
                continue  # both sides wrote under SOME lock
            findings.append(Finding(
                "PTC003", "warning",
                f"self.{attr} written from thread target (line {tl}"
                f"{'' if tg else ', unguarded'}) and public method "
                f"(line {pl}{'' if pg else ', unguarded'}) without a "
                "shared lock in scope — guard both sides or make the "
                "handoff explicit",
                filename, min(tl, pl), cls=cm.name, locks=(),
                waived=waived(tl, "PTC003") or waived(pl, "PTC003")))

    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


def lint_file(path):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, filename=path)


def lint_tree(root, skip=("fluid",)):
    """Lint every ``*.py`` under ``root`` (recursively); ``skip`` names
    top-level subpackages excluded from the sweep (the fluid compat
    layer is single-threaded API surface, not host-runtime code)."""
    findings = []
    root = os.path.abspath(root)
    for dirpath, dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        top = rel.split(os.sep)[0]
        if top in skip:
            dirnames[:] = []
            continue
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, fn)))
    return findings


def gate_findings(findings, codes=("PTC001", "PTC002")):
    """The CI gate's view: unwaived findings whose code is in
    ``codes`` (PTC003 is advisory — it warns, it does not fail)."""
    return [f for f in findings if f.code in codes and not f.waived]
