"""Recommender system: dual-tower user/item model + DeepFM-style ranker.

Ref (capability target): book ch.5,
python/paddle/fluid/tests/book/test_recommender_system.py — user tower
(id/gender/age/job embeddings -> fc) and movie tower (id/category/title
-> fc), cosine similarity scaled to a 0-5 rating, squared loss. DeepFM
adds the factorization-machine + deep ranker used by the Fluid-era
PaddleRec models.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, LayerList
from ...nn.layers.common import Linear, Embedding
from ...nn import functional as F

__all__ = ["TwoTowerRecommender", "DeepFM", "rating_loss"]


class TwoTowerRecommender(Layer):
    """Dual-tower matching model; score = 5 * cos_sim(user, item)."""

    def __init__(self, n_users, n_items, n_genders=2, n_ages=7, n_jobs=21,
                 n_categories=19, embed_dim=32, hidden=200):
        super().__init__()
        self.u_id = Embedding(n_users, embed_dim)
        self.u_gender = Embedding(n_genders, 16)
        self.u_age = Embedding(n_ages, 16)
        self.u_job = Embedding(n_jobs, 16)
        self.u_fc = Linear(embed_dim + 48, hidden)
        self.i_id = Embedding(n_items, embed_dim)
        self.i_cat = Embedding(n_categories, embed_dim)
        self.i_fc = Linear(2 * embed_dim, hidden)

    def user_tower(self, uid, gender, age, job):
        h = ops.concat([self.u_id(uid), self.u_gender(gender),
                        self.u_age(age), self.u_job(job)], axis=-1)
        return F.tanh(self.u_fc(h))

    def item_tower(self, iid, cat):
        h = ops.concat([self.i_id(iid), self.i_cat(cat)], axis=-1)
        return F.tanh(self.i_fc(h))

    def forward(self, uid, gender, age, job, iid, cat):
        u = self.user_tower(uid, gender, age, job)
        i = self.item_tower(iid, cat)
        sim = F.cosine_similarity(u, i, axis=-1)
        return 5.0 * sim


class DeepFM(Layer):
    """FM second-order interactions + deep MLP over shared embeddings.

    fields: list of vocabulary sizes, one sparse feature per field.
    """

    def __init__(self, fields, embed_dim=16, hidden=(400, 400, 400)):
        super().__init__()
        self.embeds = LayerList([Embedding(v, embed_dim) for v in fields])
        self.linears = LayerList([Embedding(v, 1) for v in fields])
        dims = [len(fields) * embed_dim] + list(hidden)
        self.mlp = LayerList([Linear(dims[i], dims[i + 1])
                              for i in range(len(hidden))])
        self.out = Linear(dims[-1], 1)

    def forward(self, *field_ids):
        """field_ids: one (B,) int tensor per field -> (B,) logit."""
        vs = [emb(ids) for emb, ids in zip(self.embeds, field_ids)]  # (B,E)
        first = ops.concat([lin(ids) for lin, ids in
                            zip(self.linears, field_ids)], axis=-1)
        first = ops.sum(first, axis=-1)                      # (B,)
        V = ops.stack(vs, axis=1)                            # (B, F, E)
        sum_sq = ops.sum(V, axis=1) ** 2                     # (B, E)
        sq_sum = ops.sum(V * V, axis=1)
        fm = 0.5 * ops.sum(sum_sq - sq_sum, axis=-1)         # (B,)
        h = ops.reshape(V, [V.shape[0], -1])
        for fc in self.mlp:
            h = F.relu(fc(h))
        deep = ops.squeeze(self.out(h), -1)
        return first + fm + deep


def rating_loss(model, uid, gender, age, job, iid, cat, rating):
    pred = model(uid, gender, age, job, iid, cat)
    return F.mse_loss(pred, rating)
