"""Recommender model zoo (ref: book ch5 recommender system)."""
from .recommender import TwoTowerRecommender, DeepFM, rating_loss  # noqa: F401
