"""word2vec: N-gram neural LM + skip-gram with negative sampling.

Ref (capability target): book ch.4,
python/paddle/fluid/tests/book/test_word2vec.py — the N-gram model embeds
4 context words, concats, hidden layer, softmax over the vocab. The
skip-gram variant adds the modern negative-sampling objective (the
reference trains it with hsigmoid/nce ops). TPU-native: both are pure
embedding-lookup + matmul graphs, ideal MXU shapes when batched.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer
from ...nn.layers.common import Linear, Embedding
from ...nn import functional as F

__all__ = ["NGramLM", "SkipGram", "skipgram_loss"]


class NGramLM(Layer):
    """Embeds ``context_size`` words; predicts the next word."""

    def __init__(self, vocab_size, embed_dim=32, hidden=256, context_size=4):
        super().__init__()
        self.context_size = context_size
        self.embed = Embedding(vocab_size, embed_dim)
        self.fc1 = Linear(context_size * embed_dim, hidden)
        self.fc2 = Linear(hidden, vocab_size)

    def forward(self, words):
        """words: (B, context_size) int ids -> (B, vocab) logits."""
        e = self.embed(words)                       # (B, C, E)
        e = ops.reshape(e, [e.shape[0], -1])
        h = F.relu(self.fc1(e))
        return self.fc2(h)


class SkipGram(Layer):
    """Center/context embedding towers; score = dot product."""

    def __init__(self, vocab_size, embed_dim=64):
        super().__init__()
        self.center = Embedding(vocab_size, embed_dim)
        self.context = Embedding(vocab_size, embed_dim)

    def forward(self, center, context):
        """(B,) center ids x (B, K) candidate ids -> (B, K) logits."""
        c = self.center(center)                     # (B, E)
        t = self.context(context)                   # (B, K, E)
        return ops.squeeze(ops.matmul(t, ops.unsqueeze(c, -1)), -1)


def skipgram_loss(model, center, context, label):
    """Negative-sampling BCE: label 1 for true context, 0 for negatives."""
    logits = model(center, context)
    return F.binary_cross_entropy_with_logits(logits, label)
