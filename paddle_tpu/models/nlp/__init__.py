"""NLP model zoo (ref: book ch4/6/8 + BERT/ERNIE/GPT era models)."""
from . import gpt  # noqa: F401
from .gpt import GPT, GPTConfig, gpt_loss, gpt_tiny, gpt_small  # noqa: F401
from .word2vec import NGramLM, SkipGram, skipgram_loss  # noqa: F401
from .sentiment import ConvSentiment, StackedLSTMSentiment  # noqa: F401
from .transformer import WMTTransformer, wmt_loss, position_encoding  # noqa: F401
from .bert import (BertConfig, BertModel, BertForPretraining, bert_base,  # noqa: F401
                   bert_tiny, bert_pretrain_loss)
