"""GPT: decoder-only LM — the 4D-parallel flagship.

Ref (capability target): the reference's ERNIE/GPT-era model-parallel LMs
built on c_allgather/c_reducescatter collective ops and Fleet hybrid
parallelism. TPU-native design:

- dp: batch sharded on the 'data' mesh axis (grad psum by GSPMD)
- tp: Column/RowParallel projections + VocabParallelEmbedding over 'model'
- sp: activations sharded along sequence on 'sp' between attention blocks
  (Megatron-SP style via sharding constraints); ring attention
  (dist/ring_attention.py) is the long-context attention path
- pp: GPTPipeline stacks per-layer params on a leading stage axis and runs
  the GPipe schedule over the 'pipe' axis
- everything compiles into ONE donated XLA executable via
  DistributedTrainStep; bf16 activations with f32 softmax/normalization.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import ops
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn.layer import Layer, LayerList
from ...nn import initializer as I
from ...nn.layers.common import Linear, Dropout, Embedding
from ...nn.layers.norm import LayerNorm
from ...dist.tp_layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, mark_sharding,
                               _constrain)
from ...dist.env import get_mesh
from ...nn.layers.transformer import MultiHeadAttention as _MHA

StaticKVCache = _MHA.StaticKVCache  # shared fixed-size KV-cache record

__all__ = ["GPTConfig", "GPT", "GPTBlock", "gpt_loss", "GPTPipeline",
           "gpt_tiny", "gpt_small"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden=768, layers=12, heads=12,
                 max_seq=1024, dropout=0.1, mp_axis="model", sp_axis="sp",
                 use_ring_attention=False, dtype="float32",
                 initializer_range=0.02, use_recompute=False):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.max_seq = max_seq
        self.dropout = dropout
        self.mp_axis = mp_axis
        self.sp_axis = sp_axis
        self.use_ring_attention = use_ring_attention
        self.dtype = dtype
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute  # jax.checkpoint per block


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden=128, layers=2, heads=4,
                     max_seq=128, **kw)


def gpt_small(**kw):
    return GPTConfig(vocab_size=50304, hidden=768, layers=12, heads=12, **kw)


def _sp_constrain(x, cfg):
    """Shard activations (B, L, D) along sequence on the sp axis."""
    mesh = get_mesh()
    if mesh is not None and cfg.sp_axis in mesh.shape and \
            mesh.shape[cfg.sp_axis] > 1:
        return _constrain(x, (None, cfg.sp_axis, None))
    return x


class GPTAttention(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.heads = cfg.heads
        self.head_dim = cfg.hidden // cfg.heads
        std = cfg.initializer_range
        self.qkv = ColumnParallelLinear(
            cfg.hidden, 3 * cfg.hidden, gather_output=False,
            weight_attr=I.Normal(0.0, std), mp_axis=cfg.mp_axis)
        self.proj = RowParallelLinear(
            cfg.hidden, cfg.hidden, input_is_parallel=True,
            weight_attr=I.Normal(0.0, std / math.sqrt(2 * cfg.layers)),
            mp_axis=cfg.mp_axis)
        self.drop = Dropout(cfg.dropout)

    def forward(self, x, cache=None):
        B, L = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        q, k, v = ops.split(qkv, 3, axis=-1)

        def heads_of(t, l):
            t = ops.reshape(t, [B, l, self.heads, self.head_dim])
            return ops.transpose(t, [0, 2, 1, 3])

        q, k, v = heads_of(q, L), heads_of(k, L), heads_of(v, L)
        if isinstance(cache, StaticKVCache):
            return self._forward_static_kv(q, k, v, cache, B, L)
        new_cache = None
        if cache is not None:
            pk, pv = cache
            k = ops.concat([pk, k], axis=2)
            v = ops.concat([pv, v], axis=2)
            new_cache = (k, v)
        mesh = get_mesh()
        if self.cfg.use_ring_attention and cache is None and \
                mesh is not None and self.cfg.sp_axis in mesh.shape and \
                mesh.shape[self.cfg.sp_axis] > 1:
            from ...dist.ring_attention import ring_attention

            att = ring_attention(q, k, v, axis_name=self.cfg.sp_axis,
                                 causal=True)
        else:
            att = F.sdpa_bhld(q, k, v, is_causal=cache is None,
                              dropout_p=self.cfg.dropout,
                              training=self.training)
        att = ops.reshape(ops.transpose(att, [0, 2, 1, 3]),
                          [B, L, self.cfg.hidden])
        out = self.drop(self.proj(att))
        return out if cache is None and new_cache is None else (out, new_cache)

    def _forward_static_kv(self, q, k_new, v_new, cache, B, L):
        """Incremental attention against fixed-size KV buffers — the
        shared jittable decode core (nn/layers/transformer.py
        static_kv_attention) plus this block's output projection."""
        from ...nn.layers.transformer import static_kv_attention

        att, new_cache = static_kv_attention(
            q, k_new, v_new, cache, dropout_p=self.cfg.dropout,
            training=self.training)
        att = ops.reshape(ops.transpose(att, [0, 2, 1, 3]),
                          [B, L, self.cfg.hidden])
        out = self.drop(self.proj(att))
        return out, new_cache


class GPTBlock(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.ln1 = LayerNorm(cfg.hidden)
        self.attn = GPTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden)
        std = cfg.initializer_range
        self.fc1 = ColumnParallelLinear(cfg.hidden, 4 * cfg.hidden,
                                        gather_output=False,
                                        weight_attr=I.Normal(0.0, std),
                                        mp_axis=cfg.mp_axis)
        self.fc2 = RowParallelLinear(4 * cfg.hidden, cfg.hidden,
                                     input_is_parallel=True,
                                     weight_attr=I.Normal(
                                         0.0, std / math.sqrt(2 * cfg.layers)),
                                     mp_axis=cfg.mp_axis)
        self.drop = Dropout(cfg.dropout)

    def forward(self, x, cache=None):
        if cache is None:
            x = x + self.attn(self.ln1(x))
            x = _sp_constrain(x, self.cfg)
            x = x + self.drop(self.fc2(F.gelu(self.fc1(self.ln2(x)),
                                              approximate=True)))
            return _sp_constrain(x, self.cfg)
        att, new_cache = self.attn(self.ln1(x), cache=cache)
        x = x + att
        x = x + self.drop(self.fc2(F.gelu(self.fc1(self.ln2(x)),
                                          approximate=True)))
        return x, new_cache


class GPT(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        std = cfg.initializer_range
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden,
                                          weight_attr=I.Normal(0.0, std),
                                          mp_axis=cfg.mp_axis)
        self.wpe = Embedding(cfg.max_seq, cfg.hidden,
                             weight_attr=I.Normal(0.0, std))
        self.drop = Dropout(cfg.dropout)
        self.blocks = LayerList([GPTBlock(cfg) for _ in range(cfg.layers)])
        self.ln_f = LayerNorm(cfg.hidden)
        # LM head tied to wte (ref: weight sharing in GPT); logits computed
        # against the (vocab-sharded) embedding matrix
        if cfg.dtype != "float32":
            self.astype(cfg.dtype)

    def forward(self, ids, cache=None):
        B, L = ids.shape[0], ids.shape[1]
        if cache is None:
            pos = ops.arange(0, L, dtype="int64")
        elif isinstance(cache[0], StaticKVCache):
            # write index (possibly traced) is the global position;
            # int32 — positions fit trivially and x64 is never enabled
            idx = cache[0].idx
            idx = idx._data if isinstance(idx, Tensor) else idx
            pos = Tensor(jnp.arange(L, dtype=jnp.int32) +
                         jnp.asarray(idx, jnp.int32), _internal=True)
        else:
            pos = ops.arange(cache[0][0].shape[2],
                             cache[0][0].shape[2] + L, dtype="int64")
        x = self.wte(ids) + self.wpe(pos)
        x = self.drop(x)
        x = _sp_constrain(x, self.cfg)
        new_caches = [] if cache is not None else None
        for i, blk in enumerate(self.blocks):
            if cache is None:
                if self.cfg.use_recompute and self.training:
                    from ...framework.recompute import recompute

                    x = recompute(blk, x)
                else:
                    x = blk(x)
            else:
                x, c = blk(x, cache=cache[i])
                new_caches.append(c)
        x = self.ln_f(x)
        logits = ops.matmul(x, ops.transpose(self.wte.weight, [1, 0]))
        logits = _constrain(logits, (None, None, None)) if \
            get_mesh() is not None else logits
        return logits if cache is None else (logits, new_caches)

    def set_recompute(self, value=True):
        """fleet protocol: DistributedStrategy.recompute toggles this."""
        self.cfg.use_recompute = bool(value)

    def init_cache(self, batch_size):
        import numpy as np

        shape = (batch_size, self.cfg.heads, 0, self.cfg.hidden // self.cfg.heads)
        z = Tensor(jnp.zeros(shape, self.wte.weight.dtype), _internal=True)
        return [(z, z) for _ in range(self.cfg.layers)]

    def init_static_cache(self, batch_size, max_length):
        """Fixed-size per-layer KV buffers for the jittable decode."""
        shape = (batch_size, self.cfg.heads, max_length,
                 self.cfg.hidden // self.cfg.heads)
        return [StaticKVCache(
            Tensor(jnp.zeros(shape, self.wte.weight.dtype), _internal=True),
            Tensor(jnp.zeros(shape, self.wte.weight.dtype), _internal=True),
            jnp.zeros((), jnp.int32)) for _ in range(self.cfg.layers)]

    def generate(self, ids, max_new_tokens=32, temperature=1.0, top_k=None):
        """Greedy/sampled decode with KV cache (eager path)."""
        import numpy as np

        cache = self.init_cache(ids.shape[0])
        out = ids
        cur = ids
        for _ in range(max_new_tokens):
            logits, cache = self.forward(cur, cache=cache)
            last = logits[:, -1]
            if temperature == 0.0:
                nxt = ops.argmax(last, axis=-1, keepdim=True)
            else:
                last = last / temperature
                if top_k is not None:
                    kth = ops.topk(last, top_k, axis=-1)[0][:, -1:]
                    last = ops.where(last < kth,
                                     ops.full_like(last, -1e30), last)
                probs = F.softmax(last, axis=-1)
                nxt = ops.multinomial(probs, 1)
            nxt = nxt.astype("int64")
            out = ops.concat([out, nxt], axis=1)
            cur = nxt
        return out

    # -- single-executable decode (static KV cache + lax.scan) -------------
    def _traced_generate(self, ids, key, *, max_new_tokens, temperature,
                         top_k):
        from ...inference.decoder import tree_unwrap, tree_wrap

        B, Lp = ids.shape
        max_len = Lp + max_new_tokens
        caches = self.init_static_cache(B, max_len)

        def pick(last, k):  # last: (B, V) raw array
            if temperature == 0.0:
                return jnp.argmax(last, axis=-1)
            logits = last.astype(jnp.float32) / temperature
            if top_k is not None:
                kth = jax.lax.top_k(logits, int(top_k))[0][:, -1:]
                logits = jnp.where(logits < kth, -1e30, logits)
            return jax.random.categorical(k, logits, axis=-1)

        keys = jax.random.split(key, max_new_tokens)
        logits, caches = self.forward(Tensor(ids, _internal=True),
                                      cache=caches)  # prefill
        nxt = pick(logits._data[:, -1], keys[0])

        def body(carry, k):
            cur, st = carry
            lg, st_t = self.forward(
                Tensor(cur[:, None], _internal=True), cache=tree_wrap(st))
            tok = pick(lg._data[:, -1], k)
            return (tok, tree_unwrap(st_t)), tok

        (_, _), toks = jax.lax.scan(body, (nxt, tree_unwrap(caches)),
                                    keys[1:])
        gen = jnp.concatenate([nxt[:, None],
                               jnp.transpose(toks, (1, 0))], axis=1) \
            if max_new_tokens > 1 else nxt[:, None]
        # int32 throughout (x64 is never enabled; values are token ids)
        return jnp.concatenate([ids.astype(jnp.int32),
                                gen.astype(jnp.int32)], axis=1)

    def generate_xla(self, ids, max_new_tokens=32, temperature=0.0,
                     top_k=None, seed=0):
        """Whole-decode jit: prefill + lax.scan token loop in ONE XLA
        executable over fixed-size KV buffers — no per-token dispatch or
        host sync (``generate`` above pays both every token). Greedy at
        temperature 0.0, else top-k/temperature sampling. One cached
        executable per (shape, knobs) signature; parameters are threaded
        as jit ARGUMENTS (not baked constants), so weight updates between
        calls are honored without retracing."""
        import functools

        from ...framework.jit import _rebind

        ids_arr = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        if max_new_tokens <= 0:  # degenerate case: eager returns prompt
            return Tensor(ids_arr.astype(jnp.int32), _internal=True)
        key = jax.random.PRNGKey(seed)
        # the active mesh shapes the traced sharding constraints, so it
        # is part of the executable's identity (tp-sharded serving)
        sig = (tuple(ids_arr.shape), int(max_new_tokens),
               float(temperature), top_k, self.training, get_mesh())
        cache = getattr(self, "_xla_gen_cache", None)
        if cache is None:
            cache = self._xla_gen_cache = {}
        if sig not in cache:
            params = list(self.parameters())
            traced = functools.partial(
                self._traced_generate, max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), top_k=top_k)

            def with_params(param_arrs, ids_a, k, _traced=traced,
                            _params=params):
                with _rebind(_params, list(param_arrs)):
                    return _traced(ids_a, k)

            cache[sig] = (params, jax.jit(with_params))
        params, fn = cache[sig]
        return Tensor(fn([p._data for p in params], ids_arr, key),
                      _internal=True)


def gpt_loss(model, ids, labels):
    """Next-token CE (labels already shifted)."""
    logits = model(ids)
    V = logits.shape[-1]
    return F.cross_entropy(ops.reshape(logits, [-1, V]),
                           ops.reshape(labels, [-1]))


class GPTPipeline:
    """Pipeline-parallel GPT (SURVEY §2 #23): per-layer block params
    stacked on a leading stage axis sharded over 'pipe'; embeddings and
    the final LN/LM-head run replicated around the GPipe schedule.

    Built FROM a ``GPT`` model — the stacked arrays are snapshots of the
    model's block weights, so single-device parity is directly testable
    and the full forward (ids -> logits) matches ``GPT.forward``.
    Homogeneous blocks make the schedule a plain lax.scan; with a
    ``batch_axis`` the same shard_map runs dp x pp.
    """

    def __init__(self, model, num_microbatches=4, axis_name="pipe",
                 batch_axis=None):
        assert isinstance(model, GPT), "build GPTPipeline from a GPT model"
        # active dropout would draw its keys once at trace time and replay
        # the same masks every step (and break GPT.forward parity)
        assert not model.training or model.cfg.dropout == 0.0, \
            "GPTPipeline needs model.eval() or cfg.dropout == 0.0"
        self.model = model
        self.cfg = model.cfg
        self.num_microbatches = num_microbatches
        self.axis_name = axis_name
        self.batch_axis = batch_axis
        self.param_names = [n for n, _ in model.blocks[0].named_parameters()]
        self.stacked = self.snapshot_blocks()

    def snapshot_blocks(self):
        """Re-stack block weights from the model (call after updates)."""
        dicts = [dict(b.named_parameters()) for b in self.model.blocks]
        return {n: jnp.stack([d[n]._data for d in dicts])
                for n in self.param_names}

    def _block_apply(self, params, x):
        """One block applied with explicit param arrays (pure, traceable)."""
        blk = self.model.blocks[0]
        named = dict(blk.named_parameters())
        from ...framework.jit import _rebind

        tensors = [named[n] for n in self.param_names]
        arrays = [params[n] for n in self.param_names]
        with _rebind(tensors, arrays):
            out = blk(Tensor(x, _internal=True))
        return out._data

    def blocks_forward(self, x, stacked=None):
        """(B, L, D) activations through the pipelined block stack."""
        from ...dist.pipeline import pipeline_forward

        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        out = pipeline_forward(self._block_apply,
                               stacked if stacked is not None
                               else self.stacked, arr,
                               self.num_microbatches, self.axis_name,
                               batch_axis=self.batch_axis)
        return Tensor(out, _internal=True) if isinstance(x, Tensor) else out

    def forward(self, ids, stacked=None):
        """Full ids -> logits, matching GPT.forward with dropout off."""
        m = self.model
        L = ids.shape[1]
        pos = ops.arange(0, L, dtype="int64")
        x = m.wte(ids) + m.wpe(pos)
        x = self.blocks_forward(x, stacked=stacked)
        x = m.ln_f(x)
        return ops.matmul(x, ops.transpose(m.wte.weight, [1, 0]))

    __call__ = forward

    def loss(self, ids, labels, stacked=None):
        logits = self.forward(ids, stacked=stacked)
        V = logits.shape[-1]
        return F.cross_entropy(ops.reshape(logits, [-1, V]),
                               ops.reshape(labels, [-1]))

    def train_step_fn(self, lr=1e-3):
        """Pure jittable SGD step over the stacked block params: proves
        grads flow back through the ppermute ring (embeddings/head stay
        frozen constants here; DistributedTrainStep owns the full-model
        path)."""

        def step(stacked, ids, labels):
            def loss_of(st):
                l = self.loss(Tensor(ids, _internal=True),
                              Tensor(labels, _internal=True), stacked=st)
                return l._data

            loss, grads = jax.value_and_grad(loss_of)(stacked)
            new = {k: v - lr * grads[k] for k, v in stacked.items()}
            return loss, new

        return step
