"""BERT encoder with MLM + NSP pretraining heads.

Ref (capability target): the reference-era BERT-Base pretrain recipe named
in BASELINE.json ("BERT-Base pretrain (Fleet CollectiveOptimizer, fp16
AMP)"). TPU-native: the encoder is jnp matmul/attention graphs that fuse
into one XLA executable; recommended recipe is bf16 autocast (amp/) +
data-parallel mesh + the pallas flash-attention path for long sequences.
"""
from __future__ import annotations

import numpy as np

from ... import ops
from ...core.tensor import Tensor
from ...nn import Layer
from ...nn.layers.common import Linear, Embedding, Dropout
from ...nn.layers.norm import LayerNorm
from ...nn.layers.transformer import TransformerEncoder, TransformerEncoderLayer
from ...nn import functional as F
from ...nn import initializer as I

__all__ = ["BertConfig", "BertModel", "BertForPretraining", "bert_base",
           "bert_tiny", "bert_pretrain_loss"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 intermediate=3072, max_position=512, type_vocab=2,
                 dropout=0.1, initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.intermediate = intermediate
        self.max_position = max_position
        self.type_vocab = type_vocab
        self.dropout = dropout
        self.initializer_range = initializer_range


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden", 128)
    kw.setdefault("layers", 2)
    kw.setdefault("heads", 2)
    kw.setdefault("intermediate", 512)
    kw.setdefault("max_position", 128)
    return BertConfig(**kw)


class BertEmbeddings(Layer):
    def __init__(self, cfg):
        super().__init__()
        std = cfg.initializer_range
        self.word = Embedding(cfg.vocab_size, cfg.hidden,
                              weight_attr=I.Normal(0.0, std))
        self.position = Embedding(cfg.max_position, cfg.hidden,
                                  weight_attr=I.Normal(0.0, std))
        self.token_type = Embedding(cfg.type_vocab, cfg.hidden,
                                    weight_attr=I.Normal(0.0, std))
        self.norm = LayerNorm(cfg.hidden)
        self.drop = Dropout(cfg.dropout)

    def forward(self, ids, token_type_ids=None):
        L = ids.shape[1]
        pos = ops.arange(0, L, dtype="int64")
        x = self.word(ids) + self.position(pos)
        if token_type_ids is not None:
            x = x + self.token_type(token_type_ids)
        return self.drop(self.norm(x))


class BertModel(Layer):
    """Encoder trunk: embeddings -> N transformer layers -> pooled [CLS]."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden, cfg.heads, cfg.intermediate, dropout=cfg.dropout,
            activation="gelu")
        self.encoder = TransformerEncoder(enc_layer, cfg.layers)
        self.pooler = Linear(cfg.hidden, cfg.hidden)

    def attn_mask(self, attention_mask):
        """(B, L) 1/0 -> additive (B, 1, 1, L) mask."""
        if attention_mask is None:
            return None
        m = (1.0 - attention_mask.astype("float32")) * -1e30
        return ops.unsqueeze(ops.unsqueeze(m, 1), 1)

    def forward(self, ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(ids, token_type_ids)
        x = self.encoder(x, src_mask=self.attn_mask(attention_mask))
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(Layer):
    """MLM (tied decoder) + NSP heads over the trunk."""

    def __init__(self, cfg):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden, cfg.hidden)
        self.transform_norm = LayerNorm(cfg.hidden)
        self.mlm_bias = self.create_parameter((cfg.vocab_size,), is_bias=True)
        self.nsp = Linear(cfg.hidden, 2)

    def forward(self, ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(ids, token_type_ids, attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq)))
        mlm_logits = ops.matmul(
            h, ops.transpose(self.bert.embeddings.word.weight, [1, 0]))
        mlm_logits = mlm_logits + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


def bert_pretrain_loss(model, ids, token_type_ids, attention_mask,
                       mlm_labels, nsp_labels, ignore_index=-100):
    """Masked-LM CE (ignore_index for unmasked positions) + NSP CE."""
    mlm_logits, nsp_logits = model(ids, token_type_ids, attention_mask)
    V = mlm_logits.shape[-1]
    mlm = F.cross_entropy(ops.reshape(mlm_logits, [-1, V]),
                          ops.reshape(mlm_labels, [-1]),
                          ignore_index=ignore_index)
    nsp = F.cross_entropy(nsp_logits, nsp_labels)
    return mlm + nsp
