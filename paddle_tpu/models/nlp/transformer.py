"""Transformer for WMT en-de machine translation.

Ref (capability target): book ch.8 machine translation
(python/paddle/fluid/tests/book/test_machine_translation.py) and the
Fluid-era Transformer WMT recipe: encoder-decoder with sinusoidal position
encoding, shared target embedding / output projection, label-smoothed CE,
and beam-search decoding (inference/decoder.py provides the beam engine;
greedy lives here).

TPU-native: one fused jitted step; decode uses the incremental
MultiHeadAttention caches so each new token is O(L) not O(L^2).
"""
from __future__ import annotations

import numpy as np

from ... import ops
from ...core.tensor import Tensor
from ...nn import Layer
from ...nn.layers.common import Linear, Embedding, Dropout
from ...nn.layers.transformer import Transformer
from ...nn import functional as F

__all__ = ["WMTTransformer", "wmt_loss", "position_encoding"]


def position_encoding(max_len, d_model):
    """Sinusoidal table (max_len, d_model), f32 numpy (baked constant)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(0, d_model, 2).astype(np.float64)
    inv = 1.0 / np.power(10000.0, dim / d_model)
    table = np.zeros((max_len, d_model), np.float32)
    table[:, 0::2] = np.sin(pos * inv)
    table[:, 1::2] = np.cos(pos * inv)
    return table


class WMTTransformer(Layer):
    """Encoder-decoder translation model with tied target softmax."""

    def __init__(self, src_vocab, tgt_vocab, d_model=512, nhead=8,
                 num_layers=6, dim_feedforward=2048, dropout=0.1,
                 max_len=256, bos_id=0, eos_id=1):
        super().__init__()
        self.d_model = d_model
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.max_len = max_len
        self.src_embed = Embedding(src_vocab, d_model)
        self.tgt_embed = Embedding(tgt_vocab, d_model)
        self.pos_table = position_encoding(max_len, d_model)
        self.drop = Dropout(dropout)
        self.transformer = Transformer(
            d_model, nhead, num_layers, num_layers, dim_feedforward, dropout)
        # output projection tied to tgt embedding (ref WMT recipe)
        self.tgt_vocab = tgt_vocab

    def _embed(self, table, ids):
        L = ids.shape[1]
        x = table(ids) * float(np.sqrt(self.d_model))
        # cast the f32 sinusoid table to the embedding dtype — an f32
        # add here would silently upcast the whole encoder for bf16
        # models (jnp promotion), halving MXU throughput
        import jax.numpy as jnp

        pos = Tensor(jnp.asarray(self.pos_table[:L], x._data.dtype),
                     _internal=True)
        return self.drop(x + pos)

    def _src_mask(self, src, pad_id=None):
        if pad_id is None:
            return None
        # (B, 1, 1, L) additive mask
        bad = ops.equal(src, ops.full_like(src, pad_id))
        m = ops.where(bad, ops.full_like(src, -1e30, dtype="float32"),
                      ops.full_like(src, 0.0, dtype="float32"))
        return ops.unsqueeze(ops.unsqueeze(m, 1), 1)

    def forward(self, src, tgt, src_pad_id=None):
        """Teacher-forced logits: (B, Lt, tgt_vocab)."""
        src_mask = self._src_mask(src, src_pad_id)
        tgt_mask = Transformer.generate_square_subsequent_mask(tgt.shape[1])
        memory = self.transformer.encoder(self._embed(self.src_embed, src),
                                          src_mask=src_mask)
        out = self.transformer.decoder(self._embed(self.tgt_embed, tgt),
                                       memory, tgt_mask=tgt_mask,
                                       memory_mask=src_mask)
        return ops.matmul(out, ops.transpose(self.tgt_embed.weight, [1, 0]))

    def encode(self, src, src_pad_id=None):
        src_mask = self._src_mask(src, src_pad_id)
        memory = self.transformer.encoder(self._embed(self.src_embed, src),
                                          src_mask=src_mask)
        return memory, src_mask

    def decode_step(self, tgt_tok, memory, caches, pos, src_mask=None):
        """One incremental decode step.

        tgt_tok: (B, 1) current token; pos: python int OR traced int32
        scalar (the lax.while_loop decode passes a tracer). Returns
        (logits (B, vocab), new caches).
        """
        x = self.tgt_embed(tgt_tok) * float(np.sqrt(self.d_model))
        if isinstance(pos, int):
            pv = self.pos_table[pos:pos + 1]
        else:
            import jax
            import jax.numpy as jnp

            p = pos._data if isinstance(pos, Tensor) else pos
            pv = jax.lax.dynamic_slice_in_dim(
                jnp.asarray(self.pos_table), p, 1, 0)
        pos_vec = Tensor(pv.astype(x._data.dtype), _internal=True)
        x = x + pos_vec
        out, new_caches = self.transformer.decoder(
            x, memory, memory_mask=src_mask, cache=caches)
        logits = ops.matmul(out[:, -1],
                            ops.transpose(self.tgt_embed.weight, [1, 0]))
        return logits, new_caches

    def greedy_decode(self, src, max_len=None, src_pad_id=None):
        """Eager greedy decode with KV caches."""
        max_len = max_len or self.max_len
        memory, src_mask = self.encode(src, src_pad_id)
        caches = self.transformer.decoder.gen_cache(memory)
        B = src.shape[0]
        cur = ops.full([B, 1], self.bos_id, dtype="int64")
        outs = [cur]
        for t in range(max_len - 1):
            logits, caches = self.decode_step(cur, memory, caches, t,
                                              src_mask)
            cur = ops.argmax(logits, axis=-1, keepdim=True).astype("int64")
            outs.append(cur)
        return ops.concat(outs, axis=1)

    def beam_search_decode(self, src, beam_size=4, max_len=None,
                           src_pad_id=None, length_penalty=0.6,
                           return_all=False):
        """Beam search through the generic decode library
        (inference/decoder.py — ref rnn.py:2699 beam_search)."""
        from ...inference import beam_search

        max_len = max_len or self.max_len
        memory, src_mask = self.encode(src, src_pad_id)
        B = src.shape[0]

        # memory/mask are identical across beams of one batch item, so they
        # stay OUT of the gathered beam state (closure instead) — only the
        # KV caches, which diverge per beam, pay the per-step reorder
        from ...inference.decoder import tile_beam

        mem_k = tile_beam(memory, beam_size)
        mask_k = tile_beam(src_mask, beam_size) if src_mask is not None \
            else None
        caches_k = self.transformer.decoder.gen_cache(mem_k)

        def step_fn(tok, caches, t):
            logits, caches = self.decode_step(tok, mem_k, caches, t, mask_k)
            return logits, caches

        return beam_search(
            step_fn, caches_k, B, self.bos_id, self.eos_id,
            beam_size, max_len, length_penalty=length_penalty,
            return_all=return_all, state_is_tiled=True)

    # -- single-executable decode (the TPU inference path) -----------------
    def _traced_beam_decode(self, src_arr, *, beam_size, max_len,
                            src_pad_id, length_penalty, return_all):
        """Encode + static-KV-cache beam loop, all inside one trace."""
        from ...inference.decoder import beam_search_xla, tile_beam

        src_t = Tensor(src_arr, _internal=True)
        memory, src_mask = self.encode(src_t, src_pad_id)
        B = src_arr.shape[0]
        mem_k = tile_beam(memory, beam_size)
        mask_k = tile_beam(src_mask, beam_size) if src_mask is not None \
            else None
        pairs = self.transformer.decoder.gen_static_cache(mem_k, max_len)
        statics = [p[1] for p in pairs]
        incs = [p[0] for p in pairs]

        def step_fn(tok, inc_state, t):
            # same body as the eager path — decode_step handles the
            # traced position; only the beam-invariant static (cross)
            # caches ride the closure instead of the gathered state
            cache = list(zip(inc_state, statics))
            logits, new_caches = self.decode_step(tok, mem_k, cache, t,
                                                  mask_k)
            return logits, [c[0] for c in new_caches]

        toks, scores = beam_search_xla(
            step_fn, incs, B, self.bos_id, self.eos_id, beam_size,
            max_len, length_penalty=length_penalty, return_all=return_all)
        return toks._data, scores._data

    def beam_search_decode_xla(self, src, beam_size=4, max_len=None,
                               src_pad_id=None, length_penalty=0.6,
                               return_all=False):
        """Whole-decode jit: encode + lax.while_loop beam search compile
        to ONE XLA executable with on-device early exit — no per-token
        host sync (the eager ``beam_search_decode`` pays a device
        round-trip every step). One executable per (batch, src_len,
        beam, max_len) signature; parameters are threaded as jit
        ARGUMENTS (not baked constants), so training between calls is
        honored without retracing."""
        import functools

        import jax
        import jax.numpy as jnp

        from ...framework.jit import _rebind

        max_len = max_len or self.max_len
        src_arr = src._data if isinstance(src, Tensor) \
            else jnp.asarray(np.asarray(src))
        key = (tuple(src_arr.shape), str(src_arr.dtype), beam_size,
               max_len, src_pad_id, length_penalty, bool(return_all),
               self.training)
        cache = getattr(self, "_xla_decode_cache", None)
        if cache is None:
            cache = self._xla_decode_cache = {}  # one executable per key
        if key not in cache:
            params = list(self.parameters())
            traced = functools.partial(
                self._traced_beam_decode, beam_size=beam_size,
                max_len=max_len, src_pad_id=src_pad_id,
                length_penalty=length_penalty, return_all=return_all)

            def with_params(param_arrs, src_a, _traced=traced,
                            _params=params):
                with _rebind(_params, list(param_arrs)):
                    return _traced(src_a)

            cache[key] = (params, jax.jit(with_params))
        params, fn = cache[key]
        toks, scores = fn([p._data for p in params], src_arr)
        return Tensor(toks, _internal=True), Tensor(scores, _internal=True)


def wmt_loss(model, src, tgt_in, tgt_label, smooth_eps=0.1, pad_id=None):
    """Label-smoothed CE over non-pad target positions."""
    logits = model(src, tgt_in, src_pad_id=pad_id)
    V = logits.shape[-1]
    flat = ops.reshape(logits, [-1, V])
    lab = ops.reshape(tgt_label, [-1])
    if smooth_eps and smooth_eps > 0.0:
        one_hot = F.one_hot(lab, V)
        soft = one_hot * (1.0 - smooth_eps) + smooth_eps / V
        logp = F.log_softmax(flat, axis=-1)
        per_tok = -ops.sum(soft * logp, axis=-1)
    else:
        per_tok = F.cross_entropy(flat, lab, reduction="none")
    if pad_id is not None:
        keep = ops.not_equal(lab, ops.full_like(lab, pad_id)).astype("float32")
        return ops.sum(per_tok * keep) / ops.maximum(
            ops.sum(keep), ops.full_like(ops.sum(keep), 1.0))
    return ops.mean(per_tok)
