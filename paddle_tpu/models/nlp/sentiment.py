"""Sentiment classification: text-CNN and stacked-LSTM nets.

Ref (capability target): book ch.6,
python/paddle/fluid/tests/book/test_understand_sentiment.py —
``convolution_net`` (sequence_conv_pool x2 widths) and
``stacked_lstm_net`` (fc+lstm stack, depth 3). TPU-native: the conv net is
a batched dense conv over the embedded sequence (MXU); the LSTM stack runs
as lax.scan cells (nn/layers/rnn.py) compiled into one fused loop.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, LayerList
from ...nn.layers.common import Linear, Embedding, Dropout
from ...nn.layers.conv import Conv1D
from ...nn.layers.rnn import LSTM
from ...nn import functional as F

__all__ = ["ConvSentiment", "StackedLSTMSentiment"]


class ConvSentiment(Layer):
    """Text-CNN: parallel conv widths -> max-pool-over-time -> FC."""

    def __init__(self, vocab_size, embed_dim=128, num_filters=128,
                 widths=(3, 4), num_classes=2, dropout=0.2):
        super().__init__()
        self.embed = Embedding(vocab_size, embed_dim)
        self.convs = LayerList([
            Conv1D(embed_dim, num_filters, w, padding=w // 2)
            for w in widths])
        self.drop = Dropout(dropout)
        self.fc = Linear(num_filters * len(widths), num_classes)

    def forward(self, ids):
        """ids: (B, L) -> (B, num_classes) logits."""
        e = self.embed(ids)                       # (B, L, E)
        x = ops.transpose(e, [0, 2, 1])           # (B, E, L) for NCL conv
        feats = []
        for conv in self.convs:
            h = F.tanh(conv(x))                   # (B, F, L')
            feats.append(ops.max(h, axis=-1))     # pool over time
        h = ops.concat(feats, axis=-1)
        return self.fc(self.drop(h))


class StackedLSTMSentiment(Layer):
    """Depth-``num_layers`` LSTM stack, final max-pool over time -> FC."""

    def __init__(self, vocab_size, embed_dim=128, hidden=128, num_layers=3,
                 num_classes=2, dropout=0.2):
        super().__init__()
        self.embed = Embedding(vocab_size, embed_dim)
        self.lstm = LSTM(embed_dim, hidden, num_layers=num_layers)
        self.drop = Dropout(dropout)
        self.fc = Linear(hidden, num_classes)

    def forward(self, ids, seq_len=None):
        e = self.embed(ids)                       # (B, L, E)
        out, _ = self.lstm(e, sequence_length=seq_len)  # (B, L, H)
        h = ops.max(out, axis=1)                  # max-pool over time
        return self.fc(self.drop(h))
