"""Model zoo (ref: the PaddlePaddle book models + ERNIE/BERT-era zoo)."""
from . import vision  # noqa: F401
from . import nlp  # noqa: F401
from . import rec  # noqa: F401
