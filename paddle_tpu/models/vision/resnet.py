"""ResNet family (18/34/50/101/152).

Ref (capability target): the reference's book ch.3 image-classification
resnet (python/paddle/fluid/tests/book/test_image_classification.py) and
the ResNet-50 ImageNet config named in BASELINE.json. TPU-native notes:
- convs stay large and batched for the MXU; BN statistics in f32.
- stride-2 3x3 convs (not the torch-style stride in 1x1) keep FLOP
  efficiency; identity downsample via 1x1 conv, Paddle "b" variant.
- `bf16=True` casts params+activations to bfloat16 with f32 BN, the
  standard TPU recipe.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, Sequential
from ...nn.layers.common import Linear
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from ...nn.layers.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn import functional as F

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "BasicBlock", "BottleneckBlock"]


class ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=(k - 1) // 2,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu(x) if self.act else x


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, cin, cout, stride=1, downsample=None):
        super().__init__()
        self.conv1 = ConvBN(cin, cout, 3, stride=stride)
        self.conv2 = ConvBN(cout, cout, 3, act=False)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.conv2(self.conv1(x))
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, cin, cout, stride=1, downsample=None):
        super().__init__()
        self.conv1 = ConvBN(cin, cout, 1)
        self.conv2 = ConvBN(cout, cout, 3, stride=stride)
        self.conv3 = ConvBN(cout, cout * 4, 1, act=False)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.conv3(self.conv2(self.conv1(x)))
        return F.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depths, num_classes=1000, in_channels=3,
                 width=64):
        super().__init__()
        self.stem = ConvBN(in_channels, width, 7, stride=2)
        self.pool = MaxPool2D(3, stride=2, padding=1)
        self.inplanes = width
        layers = []
        for i, n in enumerate(depths):
            layers.append(self._make_layer(block, width * (2 ** i), n,
                                           stride=1 if i == 0 else 2))
        self.layers = Sequential(*layers)
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc = Linear(width * (2 ** (len(depths) - 1)) * block.expansion,
                         num_classes)

    def _make_layer(self, block, planes, n, stride):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = ConvBN(self.inplanes, planes * block.expansion, 1,
                                stride=stride, act=False)
        blocks = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, n):
            blocks.append(block(self.inplanes, planes))
        return Sequential(*blocks)

    def forward(self, x):
        x = self.pool(self.stem(x))
        x = self.layers(x)
        x = self.avgpool(x)
        return self.fc(ops.flatten(x, 1))


def resnet18(**kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)


def resnet101(**kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


def resnet152(**kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], **kw)
