"""VGG family (11/13/16/19, optional BN).

Ref (capability target): book ch.3 vgg16_bn_drop in
python/paddle/fluid/tests/book/test_image_classification.py (conv blocks +
dropout + BN'd FC head).
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, Sequential
from ...nn.layers.common import Linear, Dropout
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D, BatchNorm1D
from ...nn.layers.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn.layers.activation import ReLU
from ...nn import functional as F

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    def __init__(self, depth=16, num_classes=1000, batch_norm=True,
                 in_channels=3, dropout=0.5):
        super().__init__()
        layers = []
        cin = in_channels
        for v in _CFGS[depth]:
            if v == "M":
                layers.append(MaxPool2D(2, 2))
            else:
                layers.append(Conv2D(cin, v, 3, padding=1))
                if batch_norm:
                    layers.append(BatchNorm2D(v))
                layers.append(ReLU())
                cin = v
        self.features = Sequential(*layers)
        self.avgpool = AdaptiveAvgPool2D(7)
        self.classifier = Sequential(
            Linear(512 * 7 * 7, 4096), ReLU(), Dropout(dropout),
            Linear(4096, 4096), ReLU(), Dropout(dropout),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(ops.flatten(x, 1))


def vgg11(**kw):
    return VGG(11, **kw)


def vgg13(**kw):
    return VGG(13, **kw)


def vgg16(**kw):
    return VGG(16, **kw)


def vgg19(**kw):
    return VGG(19, **kw)
