"""Faster R-CNN two-stage detector.

Ref (capability target): the reference's two-stage recipe assembled from
its core ops — rpn_target_assign (layers/detection.py:157),
generate_proposals (:2646), generate_proposal_labels (:2308),
roi_align (layers/nn.py:6680), smooth_l1 + softmax heads (the PaddleCV
Faster R-CNN configuration).

TPU-native: every stage is static shape. Anchors are host-baked
constants; proposals come back as a fixed (B, post_nms_top_n, 4) buffer
with valid counts; second-stage sampling emits dense per-roi labels and
masks instead of gathered index lists; the whole train step (backbone +
RPN losses + RoI head losses) fuses into one XLA program.
"""
from __future__ import annotations

import numpy as np

from ... import ops
from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...nn.layers.common import Linear
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from ...nn import functional as F

__all__ = ["FasterRCNN", "faster_rcnn_tiny"]


class _ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


def _make_anchors(feat_hw, stride, sizes, ratios):
    """Host-baked anchor grid (H, W, A, 4) in image coordinates."""
    H, W = feat_hw
    ws, hs = [], []
    for s in sizes:
        for r in ratios:
            ws.append(s * np.sqrt(r))
            hs.append(s / np.sqrt(r))
    ws = np.asarray(ws, np.float32)
    hs = np.asarray(hs, np.float32)
    cx = (np.arange(W, dtype=np.float32) + 0.5) * stride
    cy = (np.arange(H, dtype=np.float32) + 0.5) * stride
    out = np.zeros((H, W, len(ws), 4), np.float32)
    out[..., 0] = cy[:, None, None] * 0 + cx[None, :, None] - ws / 2
    out[..., 1] = cy[:, None, None] - hs / 2
    out[..., 2] = cx[None, :, None] + ws / 2
    out[..., 3] = cy[:, None, None] + hs / 2
    return out


class FasterRCNN(Layer):
    """Compact two-stage detector over the framework's RPN/RoI op suite.

    Single feature level; ``image_size`` fixes the anchor grid (static
    shapes end to end).
    """

    def __init__(self, num_classes=5, image_size=64, channels=32,
                 anchor_sizes=(16.0, 32.0), anchor_ratios=(1.0,),
                 post_nms_top_n=32, pooled_size=5, in_channels=3,
                 rcnn_batch_per_im=32):
        super().__init__()
        self.num_classes = num_classes
        self.image_size = image_size
        self.stride = 8
        self.post_nms_top_n = post_nms_top_n
        self.pooled = pooled_size
        self.rcnn_batch = rcnn_batch_per_im
        A = len(anchor_sizes) * len(anchor_ratios)
        self.A = A
        # backbone: stride-8 feature map
        self.c1 = _ConvBN(in_channels, channels, 3, stride=2, padding=1)
        self.c2 = _ConvBN(channels, channels, 3, stride=2, padding=1)
        self.c3 = _ConvBN(channels, channels, 3, stride=2, padding=1)
        # RPN head
        self.rpn_conv = Conv2D(channels, channels, 3, padding=1)
        self.rpn_cls = Conv2D(channels, A, 1)
        self.rpn_reg = Conv2D(channels, A * 4, 1)
        # RoI head
        head_in = channels * pooled_size * pooled_size
        self.fc1 = Linear(head_in, 64)
        self.cls_score = Linear(64, num_classes)
        self.bbox_pred = Linear(64, num_classes * 4)
        fh = image_size // self.stride
        self._anchors = _make_anchors((fh, fh), self.stride, anchor_sizes,
                                      anchor_ratios)

    def backbone(self, x):
        return self.c3(self.c2(self.c1(x)))

    def rpn(self, feat):
        h = F.relu(self.rpn_conv(feat))
        return self.rpn_cls(h), self.rpn_reg(h)

    def proposals(self, rpn_scores, rpn_deltas):
        B = rpn_scores.shape[0]
        im_info = Tensor(
            np.tile(np.asarray(
                [[self.image_size, self.image_size, 1.0]], np.float32),
                (int(B), 1)), _internal=True)
        return ops.generate_proposals(
            F.sigmoid(rpn_scores), rpn_deltas, im_info,
            Tensor(self._anchors, _internal=True), None,
            pre_nms_top_n=4 * self.post_nms_top_n,
            post_nms_top_n=self.post_nms_top_n, nms_thresh=0.7,
            min_size=2.0)

    def roi_head(self, feat, rois_flat, rois_per_im):
        pooled = ops.roi_align(
            feat, rois_flat, self.pooled, self.pooled,
            spatial_scale=1.0 / self.stride,
            rois_num=Tensor(np.full((int(feat.shape[0]),), rois_per_im,
                                    np.int32), _internal=True))
        flat = ops.reshape(pooled, [pooled.shape[0], -1])
        h = F.relu(self.fc1(flat))
        return self.cls_score(h), self.bbox_pred(h)

    def forward(self, x):
        """Inference path: (cls_scores, bbox_deltas, rois, roi_counts)."""
        feat = self.backbone(x)
        scores, deltas = self.rpn(feat)
        rois, probs, counts = self.proposals(scores, deltas)
        flat = ops.reshape(rois, [-1, 4])
        cls, reg = self.roi_head(feat, flat, self.post_nms_top_n)
        return cls, reg, rois, counts

    def loss(self, x, gt_boxes, gt_labels):
        """End-to-end two-stage loss for ONE-image batches of padded gts:
        gt_boxes (B, G, 4), gt_labels (B, G) with -1 padding."""
        feat = self.backbone(x)
        rpn_scores, rpn_deltas = self.rpn(feat)
        B = int(x.shape[0])
        total = None
        for b in range(B):  # static python loop over the (small) batch
            gb = gt_boxes[b]
            gl = gt_labels[b]
            valid = ops.greater_equal(
                gl, ops.zeros_like(gl))
            # -- RPN losses over dense anchor targets
            labels, tgt, fg, bg = ops.rpn_target_assign(
                None, None, Tensor(self._anchors, _internal=True), None,
                gb, rpn_batch_size_per_im=64, gt_valid=valid)
            s = ops.reshape(ops.transpose(
                rpn_scores[b:b + 1], [0, 2, 3, 1]), [-1])
            d = ops.reshape(ops.transpose(ops.reshape(
                rpn_deltas[b:b + 1],
                [1, self.A, 4, feat.shape[2], feat.shape[3]]),
                [0, 3, 4, 1, 2]), [-1, 4])
            pos = labels.astype("float32") * (labels.astype("float32") > 0)
            use = (labels.astype("float32") >= 0).astype("float32")
            cls_loss = ops.sum(
                F.binary_cross_entropy_with_logits(
                    s, pos.astype("float32"), reduction="none") * use
            ) / ops.maximum(ops.sum(use),
                            ops.full([], 1.0))
            fg_f = fg.astype("float32")
            reg_loss = ops.sum(
                F.smooth_l1_loss(d, tgt, reduction="none").sum(-1) * fg_f
            ) / ops.maximum(ops.sum(fg_f), ops.full([], 1.0))
            # -- proposals + second stage
            rois, probs, counts = self.proposals(
                rpn_scores[b:b + 1], rpn_deltas[b:b + 1])
            flat = ops.reshape(rois, [-1, 4])
            rlab, rtgt, rw, rfg, rbg, best = ops.generate_proposal_labels(
                flat, gl, None, gb, batch_size_per_im=self.rcnn_batch,
                class_nums=self.num_classes, gt_valid=valid)
            cls, reg = self.roi_head(feat[b:b + 1], flat,
                                     self.post_nms_top_n)
            sel = (rlab.astype("float32") >= 0).astype("float32")
            safe = ops.maximum(rlab, ops.zeros_like(rlab))
            rcnn_cls = ops.sum(
                F.cross_entropy(cls, safe, reduction="none") * sel
            ) / ops.maximum(ops.sum(sel), ops.full([], 1.0))
            reg_sel = ops.reshape(
                reg, [-1, self.num_classes, 4])
            picked = ops.take_along_axis(
                reg_sel, ops.reshape(safe, [-1, 1, 1]).astype("int64")
                .tile([1, 1, 4]), axis=1)[:, 0]
            rfg_f = rfg.astype("float32")
            rcnn_reg = ops.sum(
                F.smooth_l1_loss(picked, rtgt, reduction="none").sum(-1)
                * rfg_f) / ops.maximum(ops.sum(rfg_f), ops.full([], 1.0))
            li = cls_loss + reg_loss + rcnn_cls + rcnn_reg
            total = li if total is None else total + li
        return total / B


def faster_rcnn_tiny(num_classes=5, image_size=64):
    return FasterRCNN(num_classes=num_classes, image_size=image_size,
                      channels=16, post_nms_top_n=16, pooled_size=3,
                      rcnn_batch_per_im=16)
