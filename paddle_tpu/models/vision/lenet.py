"""LeNet-5 for MNIST.

Ref (capability target): the reference's book ch.2 recognize-digits CNN,
python/paddle/fluid/tests/book/test_recognize_digits.py (conv_net: two
conv+pool blocks then FC softmax). TPU-native: plain NCHW convs — XLA's
layout assignment picks the TPU-friendly layout, so no manual transposes.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, Sequential
from ...nn.layers.common import Linear
from ...nn.layers.conv import Conv2D
from ...nn.layers.pooling import MaxPool2D
from ...nn.layers.activation import ReLU
from ...nn import functional as F

__all__ = ["LeNet"]


class LeNet(Layer):
    """Classic LeNet-5 (num_classes logits; feed (B, 1, 28, 28))."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        self.fc = Sequential(
            Linear(400, 120), ReLU(),
            Linear(120, 84), ReLU(),
            Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = ops.flatten(x, 1)
        return self.fc(x)
