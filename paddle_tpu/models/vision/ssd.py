"""SSD single-shot detector.

Ref (capability target): the reference's SSD recipe —
layers/detection.py multi_box_head (:1971) + ssd_loss (:1390) +
detection_output (:518) over a MobileNet-style backbone (the
PaddleCV MobileNet-SSD configuration).

TPU-native: priors are baked host-side constants per feature level
(static shapes), the heads are plain convs whose outputs reshape to
(B, P, 4)/(B, P, C), and train/infer both run as one fused XLA program
through ops.ssd_loss / ops.detection_output.
"""
from __future__ import annotations

import numpy as np

from ... import ops
from ...core.tensor import Tensor
from ...nn.layer import Layer, LayerList, Sequential
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from ...nn import functional as F

__all__ = ["SSD", "ssd_tiny"]


class _ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class SSD(Layer):
    """Small multi-level SSD. ``image_size`` fixes the prior grid.

    feature_channels: channels of each detection level; the backbone
    downsamples by 2 per level starting at stride 4.
    """

    def __init__(self, num_classes=21, image_size=64,
                 feature_channels=(32, 64), min_sizes=(0.2, 0.5),
                 max_sizes=(0.5, 0.8), aspect_ratios=(2.0,),
                 in_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.image_size = image_size
        levels = len(feature_channels)
        self.stem = _ConvBN(in_channels, feature_channels[0], 3, stride=4,
                            padding=1)
        downs, locs, confs = [], [], []
        # priors per cell: len(ars)*2-1(flip)+1 min +1 max = with one ar:
        # [1, ar, 1/ar] + max -> 4
        self._ppc = 2 + 2 * len(aspect_ratios)
        cin = feature_channels[0]
        for i, ch in enumerate(feature_channels):
            if i > 0:
                downs.append(_ConvBN(cin, ch, 3, stride=2, padding=1))
            locs.append(Conv2D(ch, self._ppc * 4, 3, padding=1))
            confs.append(Conv2D(ch, self._ppc * num_classes, 3,
                                padding=1))
            cin = ch
        self.downs = LayerList(downs)
        self.locs = LayerList(locs)
        self.confs = LayerList(confs)

        # bake priors (normalized) for each level host-side
        priors = []
        s = image_size // 4
        img = np.zeros((1, 3, image_size, image_size), np.float32)
        for i in range(levels):
            feat = np.zeros((1, 1, s, s), np.float32)
            b, _ = ops.prior_box(
                Tensor(feat, _internal=True), Tensor(img, _internal=True),
                min_sizes=[min_sizes[i] * image_size],
                max_sizes=[max_sizes[i] * image_size],
                aspect_ratios=list(aspect_ratios), flip=True, clip=True)
            priors.append(np.asarray(b.numpy()).reshape(-1, 4))
            s //= 2
        self.prior_box = Tensor(np.concatenate(priors, 0), _internal=True)
        self.prior_var = [0.1, 0.1, 0.2, 0.2]

    def _heads(self, x):
        feats = [self.stem(x)]
        for d in self.downs:
            feats.append(d(feats[-1]))
        locs, confs = [], []
        B = x.shape[0]
        for f, lh, ch in zip(feats, self.locs, self.confs):
            l = lh(f)  # (B, ppc*4, H, W)
            c = ch(f)
            locs.append(ops.reshape(
                ops.transpose(l, [0, 2, 3, 1]), [B, -1, 4]))
            confs.append(ops.reshape(
                ops.transpose(c, [0, 2, 3, 1]),
                [B, -1, self.num_classes]))
        return ops.concat(locs, axis=1), ops.concat(confs, axis=1)

    def forward(self, x):
        return self._heads(x)

    def loss(self, x, gt_box, gt_label):
        loc, conf = self._heads(x)
        return ops.ssd_loss(loc, conf, gt_box, gt_label, self.prior_box,
                            self.prior_var).mean()

    def infer(self, x, score_threshold=0.3, nms_threshold=0.45,
              keep_top_k=100):
        loc, conf = self._heads(x)
        scores = F.softmax(conf, axis=-1)
        return ops.detection_output(
            loc, scores, self.prior_box, self.prior_var,
            score_threshold=score_threshold, nms_threshold=nms_threshold,
            nms_top_k=min(keep_top_k * 4, loc.shape[1]),
            keep_top_k=keep_top_k)


def ssd_tiny(num_classes=4, image_size=64):
    return SSD(num_classes=num_classes, image_size=image_size,
               feature_channels=(16, 32), min_sizes=(0.2, 0.5),
               max_sizes=(0.5, 0.8))
