"""MobileNet V1 and V2.

Ref (capability target): the reference-era mobilenet configs (depthwise-
separable convs; inverted residuals with linear bottlenecks for V2).
TPU note: depthwise convs are bandwidth-bound, not MXU-bound — XLA lowers
`feature_group_count==channels` convs to the vector unit; keeping the
pointwise 1x1 convs large preserves MXU utilization.
"""
from __future__ import annotations

from ... import ops
from ...nn import Layer, Sequential
from ...nn.layers.common import Linear, Dropout
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from ...nn.layers.pooling import AdaptiveAvgPool2D
from ...nn import functional as F

__all__ = ["MobileNetV1", "MobileNetV2"]


class _ConvBNAct(Layer):
    def __init__(self, cin, cout, k, stride=1, groups=1, act="relu6"):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=(k - 1) // 2,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self.act == "relu6":
            return F.relu6(x)
        if self.act == "relu":
            return F.relu(x)
        return x


class _DepthwiseSeparable(Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.dw = _ConvBNAct(cin, cin, 3, stride=stride, groups=cin,
                             act="relu")
        self.pw = _ConvBNAct(cin, cout, 1, act="relu")

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, num_classes=1000, scale=1.0, in_channels=3):
        super().__init__()
        def c(ch):
            return max(8, int(ch * scale))
        cfg = [(c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
               (c(128), c(256), 2), (c(256), c(256), 1), (c(256), c(512), 2),
               *[(c(512), c(512), 1)] * 5,
               (c(512), c(1024), 2), (c(1024), c(1024), 1)]
        self.stem = _ConvBNAct(in_channels, c(32), 3, stride=2, act="relu")
        self.blocks = Sequential(*[_DepthwiseSeparable(a, b, s)
                                   for a, b, s in cfg])
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.pool(self.blocks(self.stem(x)))
        return self.fc(ops.flatten(x, 1))


class _InvertedResidual(Layer):
    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hidden = int(round(cin * expand))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(_ConvBNAct(cin, hidden, 1))
        layers += [_ConvBNAct(hidden, hidden, 3, stride=stride, groups=hidden),
                   _ConvBNAct(hidden, cout, 1, act=None)]
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, num_classes=1000, scale=1.0, in_channels=3,
                 dropout=0.2):
        super().__init__()
        def c(ch):
            return max(8, int(ch * scale))
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        self.stem = _ConvBNAct(in_channels, c(32), 3, stride=2)
        blocks = []
        cin = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                blocks.append(_InvertedResidual(cin, c(ch),
                                                s if i == 0 else 1, t))
                cin = c(ch)
        self.blocks = Sequential(*blocks)
        self.head = _ConvBNAct(cin, c(1280), 1)
        self.pool = AdaptiveAvgPool2D(1)
        self.drop = Dropout(dropout)
        self.fc = Linear(c(1280), num_classes)

    def forward(self, x):
        x = self.head(self.blocks(self.stem(x)))
        x = self.drop(ops.flatten(self.pool(x), 1))
        return self.fc(x)
