"""YOLOv3 detector (compact).

Ref (capability target): the reference's YOLOv3 recipe —
layers/detection.py yolov3_loss (:895) + yolo_box (:1022) over a
Darknet-style backbone (PaddleCV yolov3 configuration).

TPU-native: fixed-size heads, dense target assignment inside
ops.yolov3_loss, and inference via ops.yolo_box + multiclass_nms — all
static shapes, one fused program each for train and infer.
"""
from __future__ import annotations

import numpy as np

from ... import ops
from ...nn.layer import Layer, LayerList, Sequential
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from ...nn import functional as F

__all__ = ["YOLOv3", "yolov3_tiny"]

_COCO_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                 116, 90, 156, 198, 373, 326]


class _ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.leaky_relu(self.bn(self.conv(x)), negative_slope=0.1)


class YOLOv3(Layer):
    """Multi-scale YOLOv3; ``anchor_masks`` selects anchors per head
    (finest first like the reference)."""

    def __init__(self, num_classes=80, anchors=None,
                 anchor_masks=((0, 1, 2), (3, 4, 5)),
                 channels=(32, 64), in_channels=3, ignore_thresh=0.7):
        super().__init__()
        self.num_classes = num_classes
        self.anchors = list(anchors or _COCO_ANCHORS)
        self.anchor_masks = [list(m) for m in anchor_masks]
        self.ignore_thresh = ignore_thresh
        # stride-8 entry, then one extra /2 per additional head
        self.stem = Sequential(_ConvBN(in_channels, channels[0], 3, 2),
                               _ConvBN(channels[0], channels[0], 3, 2),
                               _ConvBN(channels[0], channels[0], 3, 2))
        downs, heads = [], []
        cin = channels[0]
        for i, ch in enumerate(channels):
            if i > 0:
                downs.append(_ConvBN(cin, ch, 3, stride=2))
            a = len(self.anchor_masks[i])
            heads.append(Conv2D(ch, a * (5 + num_classes), 1))
            cin = ch
        self.downs = LayerList(downs)
        self.heads = LayerList(heads)
        # downsample ratio of each head relative to the input
        self.downsamples = [8 * (2 ** i) for i in range(len(channels))]

    def _feats(self, x):
        feats = [self.stem(x)]
        for d in self.downs:
            feats.append(d(feats[-1]))
        return [h(f) for h, f in zip(self.heads, feats)]

    def forward(self, x):
        return self._feats(x)

    def loss(self, x, gt_box, gt_label, gt_score=None):
        """Sum of per-head yolov3 losses, meaned over the batch."""
        outs = self._feats(x)
        total = None
        for out, mask, ds in zip(outs, self.anchor_masks,
                                 self.downsamples):
            l = ops.yolov3_loss(out, gt_box, gt_label, self.anchors,
                                mask, self.num_classes,
                                self.ignore_thresh, ds,
                                gt_score=gt_score)
            total = l if total is None else total + l
        return total.mean()

    def infer(self, x, img_size=None, conf_thresh=0.05,
              score_threshold=0.3, nms_threshold=0.45, keep_top_k=100):
        """Decode every head and NMS across all of them."""
        B, H = x.shape[0], x.shape[2]
        if img_size is None:
            img_size = ops.tile(
                ops.reshape(ops.to_tensor(
                    np.asarray([H, x.shape[3]], np.int32)), [1, 2]),
                [B, 1])
        outs = self._feats(x)
        boxes, scores = [], []
        for out, mask, ds in zip(outs, self.anchor_masks,
                                 self.downsamples):
            sub = [self.anchors[2 * i + j] for i in mask for j in (0, 1)]
            b, s = ops.yolo_box(out, img_size, sub, self.num_classes,
                                conf_thresh, ds)
            boxes.append(b)
            scores.append(s)
        boxes = ops.concat(boxes, axis=1)
        scores = ops.concat(scores, axis=1)
        return ops.multiclass_nms(
            boxes, ops.transpose(scores, [0, 2, 1]),
            score_threshold=score_threshold,
            nms_top_k=min(keep_top_k * 4, boxes.shape[1]),
            keep_top_k=keep_top_k, nms_threshold=nms_threshold,
            background_label=-1)


def yolov3_tiny(num_classes=4):
    return YOLOv3(num_classes=num_classes,
                  anchors=[10, 14, 23, 27, 37, 58, 81, 82, 135, 169,
                           344, 319],
                  anchor_masks=((0, 1, 2), (3, 4, 5)),
                  channels=(16, 32))
