"""Vision model zoo (ref: book ch2/3 — LeNet/MNIST, ResNet/VGG/MobileNet)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import MobileNetV1, MobileNetV2  # noqa: F401
from .ssd import SSD, ssd_tiny  # noqa: F401
from .faster_rcnn import FasterRCNN, faster_rcnn_tiny  # noqa: F401
from .yolov3 import YOLOv3, yolov3_tiny  # noqa: F401
