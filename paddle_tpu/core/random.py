"""Global RNG state.

TPU-native analog of the reference's ``paddle/fluid/framework/generator.cc``:
instead of a stateful Philox generator per device, we keep a root jax PRNG key
and split deterministically. Eager ops draw fresh subkeys; traced code must
thread keys explicitly (``paddle_tpu.jit`` threads one automatically).
"""
from __future__ import annotations

import jax

_STATE = {"seed": 0, "count": 0}


def seed(s: int) -> None:
    """Set the global seed (ref: fluid.default_main_program().random_seed)."""
    _STATE["seed"] = int(s)
    _STATE["count"] = 0


def get_seed() -> int:
    return _STATE["seed"]


def next_key():
    """A fresh subkey. Host-stateful in eager mode; inside a key_context
    (e.g. a paddle_tpu.jit traced step) it splits from the threaded traced
    key instead, so stochastic ops vary per step under one compilation."""
    if _STATE.get("ctx") is not None:
        _STATE["ctx"], sub = jax.random.split(_STATE["ctx"])
        return sub
    k = jax.random.fold_in(jax.random.PRNGKey(_STATE["seed"]), _STATE["count"])
    _STATE["count"] += 1
    return k


import contextlib


@contextlib.contextmanager
def key_context(key):
    """Thread a (possibly traced) key through stochastic ops."""
    prev = _STATE.get("ctx")
    _STATE["ctx"] = key
    try:
        yield
    finally:
        _STATE["ctx"] = prev


def key_for(*, salt: int = 0):
    """Deterministic key from the global seed; safe to call at trace time."""
    return jax.random.fold_in(jax.random.PRNGKey(_STATE["seed"]), salt)
