"""Tensor: the eager (dygraph) value type.

TPU-native analog of the reference's ``VarBase``/``LoDTensor``
(``paddle/fluid/imperative/layer.h``, ``framework/lod_tensor.h``): a thin
wrapper over an immutable ``jax.Array`` plus Paddle's ``stop_gradient``
autograd contract. Ragged (LoD) data is represented as dense data + explicit
offset arrays (see ops/sequence.py) — dynamic shapes don't tile onto the MXU,
so the dense+offsets layout is the TPU-correct encoding.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dispatch
from .dtype import convert_dtype

_tensor_id = [0]


def as_device_array(v):
    """Canonical value -> jax-array coercion for step-path inputs.

    Tensors unwrap to their backing array; arrays that are ALREADY on
    device (e.g. placed by the DevicePrefetcher, possibly committed to
    a sharding and still in flight) pass through UNTOUCHED — routing
    them via ``np.asarray`` would block on a device->host gather and
    re-upload with default placement, losing both the transfer overlap
    and the layout. Every feed/batch ingestion site (Executor.run /
    run_steps, TrainStep.__call__ / run_fused) must use this one
    helper so the pass-through invariant can't silently regress in a
    single copy."""
    if isinstance(v, Tensor):
        v = v._data
    if isinstance(v, jax.Array):
        return v
    return jnp.asarray(np.asarray(v))


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "name", "persistable", "_id")

    def __init__(self, data, dtype=None, stop_gradient=True, name=None, _internal=False):
        if isinstance(data, Tensor):
            data = data._data
        if _internal:
            self._data = data
        else:
            if dtype is not None:
                dtype = convert_dtype(dtype)
            elif isinstance(data, (bool, int)):
                dtype = jnp.int32 if isinstance(data, int) and not isinstance(data, bool) else jnp.bool_
            elif isinstance(data, float):
                dtype = jnp.float32
            elif isinstance(data, np.ndarray) and data.dtype == np.float64:
                dtype = jnp.float32
            self._data = jnp.asarray(data, dtype=dtype)
        self.stop_gradient = stop_gradient
        self.grad = None
        _tensor_id[0] += 1
        self._id = _tensor_id[0]
        self.name = name or f"tensor_{self._id}"
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def T(self):
        return dispatch.apply("transpose", lambda x: jnp.swapaxes(x, -2, -1) if x.ndim >= 2 else x, self)

    @property
    def is_leaf(self):
        return True  # overwritten per-instance semantics not needed: leaves tracked by tape

    def numpy(self):
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def numel(self):
        return self.size

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, _internal=True)
        return t

    def clone(self):
        return dispatch.apply("clone", lambda x: x + jnp.zeros((), x.dtype), self)

    def astype(self, dtype):
        d = convert_dtype(dtype)
        return dispatch.apply("cast", lambda x: x.astype(d), self)

    cast = astype

    def _replace(self, arr):
        """In-place value rebind (ref: VarBase::SetValue). Breaks no tape."""
        self._data = arr
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        arr = jnp.asarray(value, dtype=self._data.dtype).reshape(self._data.shape)
        if arr is value:
            # force a copy: aliasing the source buffer would let a later
            # donated-buffer step (TrainStep/Executor) delete it from under
            # the source tensor (reference set_value copies too)
            arr = jnp.array(arr, copy=True)
        self._data = arr

    def copy_(self, other):
        self.set_value(other)
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd

        autograd.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def register_hook(self, hook):
        from . import autograd

        return autograd.register_hook(self, hook)

    # -- operators (minimal set; rich API monkey-patched by ops package) ----
    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={self.stop_gradient},\n{np.asarray(self._data)})"
        )

    def __float__(self):
        return float(self._data.item())

    def __int__(self):
        return int(self._data.item())

    def __bool__(self):
        return bool(self._data.item())

    def __format__(self, spec):
        if self.size == 1:
            return format(self._data.item(), spec)
        return repr(self)

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return dispatch.apply("slice", lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            value = value._data
        self._data = self._data.at[idx].set(value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by paddle_tpu.ops (monkey_patch_tensor)

    # jax interop: allow jnp.asarray(tensor) inside user code
    def __jax_array__(self):
        return self._data

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx


class Parameter(Tensor):
    """Trainable tensor (ref: framework::Parameter / ParamBase)."""

    # _declared_sharding_spec stays UNSET until fleet.auto_parallel_step
    # stashes the layer-declared spec there before installing a plan's
    # placement (hasattr == "already stashed")
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "sharding_spec", "_declared_sharding_spec")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name, _internal=isinstance(data, jax.Array))
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.sharding_spec = None  # PartitionSpec set by TP layers / fleet

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()

    def __deepcopy__(self, memo):
        # A copied layer must NOT share parameter *names* with the source:
        # optimizer accumulators / EMA shadows are keyed by name, so a name
        # collision silently cross-wires their state (e.g. deepcopy'd
        # Transformer layers). The buffer must be a fresh copy too — donated
        # jit arguments reject the same buffer appearing twice.
        from ..utils import unique_name

        p = Parameter(jnp.array(self._data, copy=True),
                      name=unique_name.generate(self.name),
                      trainable=self.trainable)
        p.optimize_attr = dict(self.optimize_attr)
        p.regularizer = self.regularizer
        p.need_clip = self.need_clip
        memo[id(self)] = p
        return p


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    del place  # XLA owns placement; sharding APIs control device layout
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
