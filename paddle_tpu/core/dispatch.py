"""Central op dispatch.

This replaces the reference's op-dispatch machinery
(``paddle/fluid/framework/operator.cc`` OperatorWithKernel::Run and
``paddle/fluid/imperative/tracer.cc``): every framework op is a *pure jax
function*. In eager (dygraph) mode we execute it immediately, recording a
vjp closure on the autograd tape when gradients are required. In static mode
a Program builder intercepts the call and records a symbolic op instead; the
Executor later re-plays the recorded graph under ``jax.jit`` so the whole
program compiles to ONE fused XLA executable (the TPU-correct analog of the
reference's op-by-op kernel launches).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

__all__ = [
    "apply",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "register_tracer",
    "current_tracer",
]

_tls = threading.local()


def _state():
    if not hasattr(_tls, "grad_enabled"):
        _tls.grad_enabled = True
        _tls.tracer_stack = []  # static-graph program builders
        _tls.tape_stack = []  # autograd tapes (innermost last)
    return _tls


def is_grad_enabled() -> bool:
    return _state().grad_enabled


@contextlib.contextmanager
def no_grad():
    st = _state()
    prev, st.grad_enabled = st.grad_enabled, False
    try:
        yield
    finally:
        st.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    st = _state()
    prev, st.grad_enabled = st.grad_enabled, True
    try:
        yield
    finally:
        st.grad_enabled = prev


# ---------------------------------------------------------------------------
# Static-graph tracer hook
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def register_tracer(tracer):
    """Push a static-graph tracer; ops are recorded instead of executed."""
    st = _state()
    st.tracer_stack.append(tracer)
    try:
        yield tracer
    finally:
        st.tracer_stack.pop()


def current_tracer():
    st = _state()
    return st.tracer_stack[-1] if st.tracer_stack else None


# ---------------------------------------------------------------------------
# Autograd tape
# ---------------------------------------------------------------------------


class TapeNode:
    __slots__ = ("inputs", "outputs", "vjp_fn", "name")

    def __init__(self, name, inputs, outputs, vjp_fn):
        self.name = name
        self.inputs = inputs  # list[Tensor]
        self.outputs = outputs  # list[Tensor]
        self.vjp_fn = vjp_fn


class Tape:
    def __init__(self):
        self.nodes: list[TapeNode] = []

    def record(self, node):
        self.nodes.append(node)

    def clear(self):
        self.nodes.clear()


def default_tape() -> Tape:
    st = _state()
    if not st.tape_stack:
        st.tape_stack.append(Tape())
    return st.tape_stack[-1]


@contextlib.contextmanager
def fresh_tape():
    """Scoped tape, used by paddle_tpu.grad() for double-backward isolation."""
    st = _state()
    t = Tape()
    st.tape_stack.append(t)
    try:
        yield t
    finally:
        st.tape_stack.pop()


# ---------------------------------------------------------------------------
# apply(): the single entry point every op goes through
# ---------------------------------------------------------------------------


def _is_tensor(x):
    from .tensor import Tensor

    return isinstance(x, Tensor)


def _unwrap(x):
    return x._data if _is_tensor(x) else x


def _wrap(arr, stop_gradient=True):
    from .tensor import Tensor

    return Tensor(arr, stop_gradient=stop_gradient, _internal=True)


def _all_float(out):
    outs = out if isinstance(out, tuple) else (out,)
    return all(jnp.issubdtype(o.dtype, jnp.inexact) for o in outs)


_amp_state = _cast_op_inputs = _nan_guard = None

# Push-style chaos hook (resilience.inject 'nan_op' corruption): None when
# no injector is active, so the disabled hot path pays one None check.
_chaos_op_hook = None

# Push-style telemetry hook (obs.enable_op_sampling): eager op counting
# is off by default and the disabled hot path pays the same one None
# check — the dispatcher cannot afford a registry probe per op.
_op_metrics_hook = None


def set_chaos_op_hook(fn):
    global _chaos_op_hook
    _chaos_op_hook = fn


def set_op_metrics_hook(fn):
    global _op_metrics_hook
    _op_metrics_hook = fn


def _lazy_hooks():
    """Bind the AMP / nan-guard hooks once (module-level import would be a
    cycle: amp.grad_scaler -> core.tensor -> core.dispatch)."""
    global _amp_state, _cast_op_inputs, _nan_guard
    if _amp_state is None:
        from ..amp.autocast import amp_state, cast_op_inputs
        from ..utils import nan_guard

        _amp_state, _cast_op_inputs, _nan_guard = \
            amp_state, cast_op_inputs, nan_guard


def apply(name, fn, *args, **attrs):
    """Run op ``name`` implemented by pure function ``fn``.

    ``args`` are tensor-like (differentiable) inputs; ``attrs`` are static
    python attributes baked into the computation (ref: OpDesc attrs).
    ``fn(*arrays, **attrs)`` must be jax-traceable and return one array or a
    tuple of arrays.
    """
    tracer = current_tracer()
    if tracer is not None:
        return tracer.trace_op(name, fn, args, attrs)

    if _op_metrics_hook is not None:  # eager executions only: a recorded
        _op_metrics_hook(name)        # static op is not a dispatch

    arrays = [_unwrap(a) for a in args]
    need_grad = is_grad_enabled() and any(
        _is_tensor(a) and not a.stop_gradient for a in args
    )

    # AMP: cast inputs per the active auto_cast policy INSIDE the
    # differentiated function, so grads flow back in the original dtype and
    # XLA fuses the casts into the op (paddle_tpu.amp.auto_cast). The
    # helpers are imported once (cycle-safe) and the no-AMP hot path avoids
    # any extra closure.
    _lazy_hooks()
    if _amp_state() is not None:
        op_fn = lambda *xs: fn(*_cast_op_inputs(name, xs), **attrs)  # noqa: E731
        if need_grad:
            out, vjp_fn = jax.vjp(op_fn, *arrays)
        else:
            out = op_fn(*arrays)
    elif need_grad:
        out, vjp_fn = jax.vjp(lambda *xs: fn(*xs, **attrs), *arrays)
    else:
        out = fn(*arrays, **attrs)
    if need_grad and not _all_float(out):
        # Non-differentiable outputs (argmax, comparisons...): keep the
        # values, drop the tape record.
        need_grad = False

    multi = isinstance(out, tuple)
    outs = out if multi else (out,)

    if _chaos_op_hook is not None and not isinstance(
            outs[0], jax.core.Tracer):
        # chaos corruption BEFORE the nan-guard check, so detection sees
        # the injected fault; never under a trace (a corrupted tracer
        # would bake NaN into the compiled function permanently)
        outs = _chaos_op_hook(name, outs)

    if _nan_guard.check_nan_enabled() and not isinstance(
            outs[0], jax.core.Tracer):
        _nan_guard.check_op_outputs(name, outs)

    out_tensors = tuple(_wrap(o, stop_gradient=not need_grad) for o in outs)

    if need_grad:
        in_tensors = [a if _is_tensor(a) else None for a in args]
        default_tape().record(
            TapeNode(name, in_tensors, list(out_tensors), vjp_fn)
        )
    return out_tensors if multi else out_tensors[0]
