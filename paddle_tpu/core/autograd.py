"""Eager autograd engine.

TPU-native analog of the reference's ``paddle/fluid/imperative/basic_engine.cc``
(+ ``partial_grad_engine.cc`` for ``paddle.grad``): instead of registered
per-op grad kernels, every taped op carries the ``jax.vjp`` closure captured at
forward time, so backward is a reverse walk calling XLA-compiled vjps. The
walk itself is jax-traceable, which lets a whole dygraph train step be wrapped
in ``jax.jit`` and fuse forward+backward+update into one executable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import dispatch
from .dispatch import default_tape, fresh_tape
from .tensor import Tensor

_hooks: dict[int, list] = {}


def register_hook(tensor: Tensor, hook):
    _hooks.setdefault(tensor._id, []).append(hook)

    class _Removable:
        def remove(self):
            _hooks.get(tensor._id, []).remove(hook)

    return _Removable()


def _walk(tape_nodes, seed_grads, retain_graph, accumulate_into_grad=True,
          wanted: dict | None = None):
    """Reverse-walk ``tape_nodes``. ``seed_grads``: {tensor_id: cotangent}.

    Returns dict of {tensor_id: cotangent} for tensors in ``wanted`` (or all
    leaves if wanted is None and accumulate_into_grad is set).
    """
    del accumulate_into_grad
    pending: dict[int, jax.Array] = dict(seed_grads)
    results: dict[int, jax.Array] = {}

    def _fire_hooks(t, g):
        for h in _hooks.get(t._id, ()):  # user hooks may transform the grad
            out = h(Tensor(g, _internal=True))
            if out is not None:
                g = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        return g

    for node in reversed(tape_nodes):
        if not any(o._id in pending for o in node.outputs):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through the graph a second time "
                "(pass retain_graph=True)"
            )
        cotangents = tuple(
            pending.get(o._id, jnp.zeros(o._data.shape, o._data.dtype))
            for o in node.outputs
        )
        # Once the producer is visited no more contributions can arrive
        # (tape order is topological), so capture wanted intermediates now.
        for o in node.outputs:
            if o._id in pending:
                if wanted is not None and o._id in wanted:
                    results[o._id] = pending[o._id]
                del pending[o._id]
        grads_in = node.vjp_fn(cotangents if len(cotangents) > 1 else cotangents[0])
        for t, g in zip(node.inputs, grads_in):
            if t is None or t.stop_gradient:
                continue
            if g.dtype == jax.dtypes.float0:
                continue
            g = _fire_hooks(t, g)
            pending[t._id] = pending[t._id] + g if t._id in pending else g
        if not retain_graph:
            node.vjp_fn = None
    for tid, g in pending.items():
        if wanted is None or tid in wanted:
            results.setdefault(tid, g)
    return results


def backward(tensor: Tensor, grad_tensor=None, retain_graph=False):
    """Populate ``.grad`` on all reachable leaves (ref: VarBase::RunBackward)."""
    tape = default_tape()
    if grad_tensor is None:
        seed = jnp.ones(tensor._data.shape, tensor._data.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    produced = {o._id for n in tape.nodes for o in n.outputs}
    id2tensor: dict[int, Tensor] = {}
    for n in tape.nodes:
        for t in n.inputs:
            if t is not None and not t.stop_gradient:
                id2tensor[t._id] = t

    with dispatch.no_grad():
        results = _walk(tape.nodes, {tensor._id: seed}, retain_graph)

    for tid, g in results.items():
        t = id2tensor.get(tid)
        if t is None or tid in produced:
            continue  # only leaves get .grad (paddle semantics)
        t.grad = Tensor(g, _internal=True) if t.grad is None else Tensor(t.grad._data + g, _internal=True)
    if not retain_graph:
        tape.clear()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """Functional gradients (ref: python/paddle/fluid/dygraph/base.py grad)."""
    del only_inputs
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    tape = default_tape()
    seeds = {}
    for o, go in zip(outputs, grad_outputs):
        g = jnp.ones(o._data.shape, o._data.dtype) if go is None else (
            go._data if isinstance(go, Tensor) else jnp.asarray(go))
        seeds[o._id] = seeds.get(o._id, 0) + g

    wanted = {t._id: t for t in inputs}
    keep = retain_graph if retain_graph is not None else create_graph
    with dispatch.no_grad():
        results = _walk(tape.nodes, seeds, keep, accumulate_into_grad=False, wanted=wanted)

    out = []
    for t in inputs:
        if t._id in results:
            out.append(Tensor(results[t._id], stop_gradient=not create_graph, _internal=True))
        elif allow_unused:
            out.append(None)
        else:
            raise RuntimeError(f"tensor {t.name} is unused in the graph (pass allow_unused=True)")
    return out
