"""Dtype registry.

TPU-native analog of the reference's ``paddle/fluid/framework/data_type.h``
(proto VarType dtypes): we map Paddle-style dtype names onto jnp dtypes and
default to bfloat16-friendly promotion on TPU.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical name -> jnp dtype. TPU-native canonicalization: 64-bit types map
# to their 32-bit counterparts (int32 is the hardware int; f64 has no TPU
# unit). The reference defaults python ints to int64 — we accept the names
# for API parity and store 32-bit.
_DTYPE_MAP = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float32,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int32,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex64,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float32  # canonicalized (no f64 unit on TPU)
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int32  # canonicalized (TPU int is 32-bit)
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex64

_DEFAULT_DTYPE = ["float32"]


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = dtype_name(d)


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def convert_dtype(d):
    """Normalize any dtype spec (str, np/jnp dtype, python type) -> np.dtype."""
    if d is None:
        d = _DEFAULT_DTYPE[0]
    if isinstance(d, str):
        d = _ALIASES.get(d, d)
        if d not in _DTYPE_MAP:
            raise ValueError(f"unknown dtype {d!r}")
        return jnp.dtype(_DTYPE_MAP[d])
    if d is float:
        return jnp.dtype(_DTYPE_MAP[_DEFAULT_DTYPE[0]])
    if d is int:
        return jnp.dtype(jnp.int32)
    if d is bool:
        return jnp.dtype(jnp.bool_)
    return jnp.dtype(d)


def dtype_name(d) -> str:
    return convert_dtype(d).name


def is_floating(d) -> bool:
    return jnp.issubdtype(convert_dtype(d), np.floating)


def is_integer(d) -> bool:
    return jnp.issubdtype(convert_dtype(d), np.integer)
