from . import dtype, device, random, dispatch
from .tensor import Tensor, Parameter, to_tensor
from .dispatch import no_grad, enable_grad, is_grad_enabled
from .autograd import backward, grad

__all__ = [
    "dtype", "device", "random", "dispatch",
    "Tensor", "Parameter", "to_tensor",
    "no_grad", "enable_grad", "is_grad_enabled", "backward", "grad",
]
