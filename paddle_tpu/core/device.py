"""Device management.

TPU-native analog of the reference's ``paddle/fluid/platform/place.h``
(CPUPlace/CUDAPlace/CUDAPinnedPlace) and ``device_context.{h,cc}``.
On TPU there is no per-op stream management — XLA owns scheduling — so a
"place" reduces to a jax.Device plus helpers for host staging.
"""
from __future__ import annotations

import functools
import jax


class Place:
    """A device placement (ref: platform::Place)."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.index == other.index
        )

    def __hash__(self):
        return hash((self.kind, self.index))

    @property
    def jax_device(self):
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:  # fall back to whatever the default backend offers
            devs = jax.devices()
        return devs[min(self.index, len(devs) - 1)]


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CPUPlace(index: int = 0) -> Place:
    return Place("cpu", index)


# The reference exposes CUDAPlace; accepting the name keeps recipes portable.
def CUDAPlace(index: int = 0) -> Place:  # pragma: no cover - alias
    return TPUPlace(index)


def _kind_of(dev) -> str:
    plat = getattr(dev, "platform", "cpu")
    return "tpu" if plat not in ("cpu",) else "cpu"


_CURRENT = [None]


def set_device(device) -> Place:
    """set_device("tpu"), set_device("cpu"), set_device("tpu:0")."""
    if isinstance(device, Place):
        _CURRENT[0] = device
        return device
    name, _, idx = str(device).partition(":")
    if name in ("gpu", "cuda", "xpu"):
        name = "tpu"
    place = Place(name, int(idx) if idx else 0)
    _CURRENT[0] = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    if _CURRENT[0] is None:
        _CURRENT[0] = Place(_kind_of(jax.devices()[0]), 0)
    return _CURRENT[0]


@functools.lru_cache(maxsize=None)
def device_count(kind: str = None) -> int:
    if kind is None:
        return len(jax.devices())
    return len([d for d in jax.devices() if _kind_of(d) == kind])


def is_compiled_with_tpu() -> bool:
    return any(_kind_of(d) == "tpu" for d in jax.devices())


def set_compilation_cache(directory, min_compile_time_secs=1.0):
    """Persist compiled XLA executables across processes (the TPU analog
    of the reference's program/kernel caches). Two layers share
    ``directory``:

    - jax's native persistent compilation cache (every jit/pjit whose
      compile took >= ``min_compile_time_secs``) — but jax declines to
      write it on some backends (notably host CPU), so
    - the framework's own AOT executable cache
      (``paddle_tpu.runtime.aot``) is activated on the SAME directory:
      every Executor/TrainStep/Predictor/ServeEngine compile is then
      serialized as a content-addressed envelope and hydrated by the
      next process — first-step latency on a tunnel-attached chip (or
      a fresh serving replica) drops from tens of seconds to
      cache-read time, on every backend.

    Pass ``None`` to disable both. Returns the directory."""
    import jax

    from ..runtime import aot as _aot

    if directory is None:
        jax.config.update("jax_enable_compilation_cache", False)
        # force-off, masking an env PADDLE_TPU_AOT_CACHE too — "pass
        # None to disable both" must hold however the cache came on
        _aot.disable()
        return None
    import os

    directory = os.path.abspath(str(directory))
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    _aot.configure(directory)
    return directory
