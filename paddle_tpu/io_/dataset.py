"""Datasets (ref: python/paddle/fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor

        self.tensors = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                        for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors), \
            "tensors must share dim 0"

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    """Zip datasets: sample i is the concatenation of each dataset's i-th."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "empty datasets"
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            s = d[idx]
            out.extend(s if isinstance(s, (tuple, list)) else (s,))
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumsizes[-1] if self.cumsizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumsizes, idx)
        prev = self.cumsizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        fracs = lengths
        lengths = [int(np.floor(n * f)) for f in fracs]
        for i in range(n - sum(lengths)):
            lengths[i % len(lengths)] += 1
    assert sum(lengths) == n, "lengths must sum to dataset size"
    rng = np.random.RandomState(generator) if isinstance(generator, int) \
        else (generator or np.random)
    perm = rng.permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out
