"""DataLoader with native-ring prefetch.

Ref: python/paddle/fluid/reader.py (DataLoader, py_reader) +
paddle/fluid/operators/reader/buffered_reader.cc.

Worker threads fetch+collate batches (numpy work releases the GIL) and push
pickled batches into the C++ ring buffer (runtime/); the train loop pops
ready batches — host input prep overlaps device compute, which is the whole
game for keeping the TPU fed. Threads, not processes: batch assembly is
numpy-bound, and jax arrays must be created in the consumer process anyway.
"""
from __future__ import annotations

import pickle
import threading

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "default_convert_fn"]


def default_convert_fn(batch):
    return batch


def default_collate_fn(batch):
    """Stack a list of samples into batch arrays (ref: default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if hasattr(sample, "_data"):  # Tensor
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(f)) for f in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


class _Prefetcher:
    """N worker threads -> native ring buffer -> ordered reassembly."""

    def __init__(self, work_iter, fetch, num_workers, capacity):
        from ..runtime import RingBuffer

        self._ring = RingBuffer(capacity)
        self._work = list(work_iter)
        self._fetch = fetch
        self._next_out = 0
        self._stash = {}
        self._cursor = 0
        self._cursor_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(num_workers)]
        self._active = len(self._threads)
        self._active_lock = threading.Lock()
        for t in self._threads:
            t.start()

    def _worker(self):
        while True:
            with self._cursor_lock:
                i = self._cursor
                self._cursor += 1
            if i >= len(self._work):
                break
            try:
                batch = self._fetch(self._work[i])
                payload = pickle.dumps((i, batch), protocol=5)
            except Exception as e:  # surface errors in the consumer
                payload = pickle.dumps((i, e), protocol=5)
            if not self._ring.push(payload):
                return
        with self._active_lock:
            self._active -= 1
            if self._active == 0:
                self._ring.close()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._next_out in self._stash:
                item = self._stash.pop(self._next_out)
                self._next_out += 1
                if isinstance(item, Exception):
                    raise item
                return item
            blob = self._ring.pop()
            if blob is None:
                if self._next_out in self._stash:
                    continue
                raise StopIteration
            i, batch = pickle.loads(blob)
            self._stash[i] = batch  # restore deterministic batch order

    def shutdown(self):
        self._ring.close()


class DataLoader:
    """ref: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last) \
                if batch_size is not None else None
            self.batch_size = batch_size

    def _fetch_batch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)

    def __iter__(self):
        from ..core.tensor import Tensor

        def to_tensors(b):
            if not self.return_list:
                return b
            if isinstance(b, (list, tuple)):
                return [Tensor(np.asarray(x), _internal=False)
                        if isinstance(x, np.ndarray) else x for x in b]
            if isinstance(b, np.ndarray):
                return [Tensor(b, _internal=False)]
            return b

        if self._iterable_mode:
            for b in self._iter_iterable():
                yield to_tensors(b)
            return
        if self.batch_sampler is None:  # no batching: raw samples
            for i in range(len(self.dataset)):
                yield to_tensors(self.dataset[i])
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield to_tensors(self._fetch_batch(indices))
            return
        pf = _Prefetcher(self.batch_sampler, self._fetch_batch,
                         self.num_workers,
                         capacity=self.num_workers * self.prefetch_factor)
        try:
            for b in pf:
                yield to_tensors(b)
        finally:
            pf.shutdown()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)
