"""DataLoader with native-ring prefetch.

Ref: python/paddle/fluid/reader.py (DataLoader, py_reader) +
paddle/fluid/operators/reader/buffered_reader.cc.

Worker threads fetch+collate batches (numpy work releases the GIL) and push
pickled batches into the C++ ring buffer (runtime/); the train loop pops
ready batches — host input prep overlaps device compute, which is the whole
game for keeping the TPU fed. Threads, not processes: batch assembly is
numpy-bound, and jax arrays must be created in the consumer process anyway.
"""
from __future__ import annotations

import pickle
import threading
import time

import numpy as np

from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import inject as _chaos
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "default_convert_fn"]

# interned once; ticked per BATCH (not per sample), so the pipeline's
# telemetry cost is noise against the numpy collate work it measures
_M_QUEUE_DEPTH = _metrics.gauge("dataloader.queue_depth")
_M_PRODUCER_WAIT = _metrics.histogram("dataloader.producer_wait_ms")
_M_CONSUMER_WAIT = _metrics.histogram("dataloader.consumer_wait_ms")
_M_RESTARTS = _metrics.counter("dataloader.worker_restarts")


def default_convert_fn(batch):
    return batch


def default_collate_fn(batch):
    """Stack a list of samples into batch arrays (ref: default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if hasattr(sample, "_data"):  # Tensor
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(f)) for f in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


class _Prefetcher:
    """N worker threads -> native ring buffer -> ordered reassembly.

    Fault model (chaos point ``loader_worker``): a per-batch FETCH error
    is data-level — it is surfaced to the consumer at its batch position
    immediately (no retry: a corrupt record won't uncorrupt, and
    re-running a side-effectful fetch is wrong). A worker THREAD death
    (an error escaping the fetch capture — injected crash, payload
    pickling failure) is infrastructure-level: a supervisor restarts a
    replacement (which re-fetches the abandoned index) within a bounded
    restart budget, and only when that budget is exhausted is the death
    surfaced, in batch order — the iterator can fail, but it can never
    hang waiting for an index a dead worker will never deliver.
    """

    def __init__(self, work_iter, fetch, num_workers, capacity,
                 max_restarts=2):
        from ..runtime import RingBuffer

        self._ring = RingBuffer(capacity)
        self._work = list(work_iter)
        self._fetch = fetch
        self._next_out = 0
        self._stash = {}
        self._cursor = 0
        self._retry: list = []  # indices abandoned by crashed workers
        self._cursor_lock = threading.Lock()
        self._restarts_left = int(max_restarts)
        self.restarts = 0  # observability: how many crashes were absorbed
        self._threads = []
        self._active = num_workers
        self._active_lock = threading.Lock()
        for _ in range(num_workers):
            # start each thread as it is created: a crashed worker may
            # append its replacement to _threads concurrently, and a
            # start-them-all-afterwards loop would start that
            # (already-running) replacement a second time
            t = threading.Thread(target=self._worker, daemon=True)
            self._threads.append(t)
            t.start()

    def _next_index(self):
        with self._cursor_lock:
            if self._retry:
                return self._retry.pop()
            i = self._cursor
            self._cursor += 1
            return i if i < len(self._work) else None

    def _worker(self):
        i = None
        try:
            while True:
                i = self._next_index()
                if i is None:
                    break
                if _chaos.ACTIVE:
                    _chaos.fire("loader_worker")  # may kill this thread
                try:
                    batch = self._fetch(self._work[i])
                    payload = pickle.dumps((i, batch), protocol=5)
                except Exception as e:
                    # data-level error: surface at this batch position
                    # (the consumer raises it in order); the worker
                    # lives on and its restart budget is untouched
                    payload = pickle.dumps((i, e), protocol=5)
                t0 = time.perf_counter()
                if not self._ring.push(payload):
                    return  # ring closed by consumer shutdown
                # blocked push = backpressure: the consumer (train loop)
                # is the bottleneck, which is the healthy direction
                _M_PRODUCER_WAIT.observe((time.perf_counter() - t0) * 1e3)
                _M_QUEUE_DEPTH.set(len(self._ring))
                i = None
        except BaseException as e:  # worker DEATH (chaos kill, pickling
            self._crashed(i, e)     # failure, machinery bug)
            return
        self._finish()

    def _crashed(self, i, exc):
        """Restart a replacement worker within budget, else surface the
        error (in batch order) so the consumer raises instead of hanging."""
        with self._active_lock:
            if self._restarts_left > 0:
                self._restarts_left -= 1
                self.restarts += 1
                _M_RESTARTS.inc()
                if _journal.ACTIVE is not None:
                    _journal.ACTIVE.event(
                        "dataloader.worker_restart", batch_index=i,
                        error=f"{type(exc).__name__}: {exc}",
                        restarts_left=self._restarts_left)
                if i is not None:
                    with self._cursor_lock:
                        self._retry.append(i)  # replacement re-fetches it
                t = threading.Thread(target=self._worker, daemon=True)
                self._threads.append(t)
                t.start()  # replacement inherits this slot: _active unchanged
                return
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event(
                "dataloader.restart_budget_exhausted", batch_index=i,
                error=f"{type(exc).__name__}: {exc}")
        if i is not None:
            if not isinstance(exc, Exception):
                exc = RuntimeError(
                    f"DataLoader worker died ({exc!r}) and the restart "
                    "budget is exhausted")
            try:
                payload = pickle.dumps((i, exc), protocol=5)
            except Exception:
                payload = pickle.dumps(
                    (i, RuntimeError(f"DataLoader worker died: {exc!r} "
                                     "(original exception unpicklable)")),
                    protocol=5)
            self._ring.push(payload)
        self._finish()

    def _finish(self):
        with self._active_lock:
            self._active -= 1
            if self._active == 0:
                self._ring.close()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._next_out in self._stash:
                item = self._stash.pop(self._next_out)
                self._next_out += 1
                if isinstance(item, Exception):
                    raise item
                return item
            t0 = time.perf_counter()
            blob = self._ring.pop()
            # a long pop = the train loop starved waiting on input — the
            # number step-time attribution cares about most
            _M_CONSUMER_WAIT.observe((time.perf_counter() - t0) * 1e3)
            if blob is None:
                if self._next_out in self._stash:
                    continue
                raise StopIteration
            _M_QUEUE_DEPTH.set(len(self._ring))
            i, batch = pickle.loads(blob)
            self._stash[i] = batch  # restore deterministic batch order

    def shutdown(self, timeout=5.0):
        """Close the ring and JOIN the workers: an iterator abandoned
        mid-epoch (or one whose consumer raised) must not leak daemon
        threads still fetching batches."""
        self._ring.close()
        import time

        deadline = time.monotonic() + timeout
        for t in list(self._threads):  # snapshot: restarts may append
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class DataLoader:
    """ref: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 max_worker_restarts=2):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.max_worker_restarts = max_worker_restarts
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last) \
                if batch_size is not None else None
            self.batch_size = batch_size

    def _fetch_batch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)

    def __iter__(self):
        from ..core.tensor import Tensor

        def to_tensors(b):
            if not self.return_list:
                return b
            if isinstance(b, (list, tuple)):
                return [Tensor(np.asarray(x), _internal=False)
                        if isinstance(x, np.ndarray) else x for x in b]
            if isinstance(b, np.ndarray):
                return [Tensor(b, _internal=False)]
            return b

        if self._iterable_mode:
            for b in self._iter_iterable():
                yield to_tensors(b)
            return
        if self.batch_sampler is None:  # no batching: raw samples
            for i in range(len(self.dataset)):
                yield to_tensors(self.dataset[i])
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                with _trace.span("dataloader.next", workers=0):
                    b = self._fetch_batch(indices)
                yield to_tensors(b)
            return
        pf = _Prefetcher(self.batch_sampler, self._fetch_batch,
                         self.num_workers,
                         capacity=self.num_workers * self.prefetch_factor,
                         max_restarts=self.max_worker_restarts)
        try:
            it = iter(pf)
            while True:
                # span covers only the wait for the prefetched batch,
                # not the consumer's processing of it
                with _trace.span("dataloader.next",
                                 workers=self.num_workers):
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                yield to_tensors(b)
        finally:
            pf.shutdown()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)
