"""DataLoader with native-ring prefetch.

Ref: python/paddle/fluid/reader.py (DataLoader, py_reader) +
paddle/fluid/operators/reader/buffered_reader.cc.

Worker threads fetch+collate batches (numpy work releases the GIL) and push
pickled batches into the C++ ring buffer (runtime/); the train loop pops
ready batches — host input prep overlaps device compute, which is the whole
game for keeping the TPU fed. Threads, not processes: batch assembly is
numpy-bound, and jax arrays must be created in the consumer process anyway.
"""
from __future__ import annotations

import pickle
import threading
import time

import numpy as np

from ..obs import journal as _journal
from ..obs import lockdep as _lockdep
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import inject as _chaos
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "default_convert_fn",
           "DevicePrefetcher", "prefetch_to_device",
           "executor_feed_shardings"]

# interned once; ticked per BATCH (not per sample), so the pipeline's
# telemetry cost is noise against the numpy collate work it measures
_M_QUEUE_DEPTH = _metrics.gauge("dataloader.queue_depth")
_M_PRODUCER_WAIT = _metrics.histogram("dataloader.producer_wait_ms")
_M_CONSUMER_WAIT = _metrics.histogram("dataloader.consumer_wait_ms")
_M_RESTARTS = _metrics.counter("dataloader.worker_restarts")
_M_DEVICE_PUTS = _metrics.counter("dataloader.device_put_batches")


def default_convert_fn(batch):
    return batch


def default_collate_fn(batch):
    """Stack a list of samples into batch arrays (ref: default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if hasattr(sample, "_data"):  # Tensor
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(f)) for f in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


class _Prefetcher:
    """N worker threads -> native ring buffer -> ordered reassembly.

    Fault model (chaos point ``loader_worker``): a per-batch FETCH error
    is data-level — it is surfaced to the consumer at its batch position
    immediately (no retry: a corrupt record won't uncorrupt, and
    re-running a side-effectful fetch is wrong). A worker THREAD death
    (an error escaping the fetch capture — injected crash, payload
    pickling failure) is infrastructure-level: a supervisor restarts a
    replacement (which re-fetches the abandoned index) within a bounded
    restart budget, and only when that budget is exhausted is the death
    surfaced, in batch order — the iterator can fail, but it can never
    hang waiting for an index a dead worker will never deliver.
    """

    def __init__(self, work_iter, fetch, num_workers, capacity,
                 max_restarts=2):
        from ..runtime import RingBuffer

        self._ring = RingBuffer(capacity)
        self._work = list(work_iter)
        self._fetch = fetch
        self._next_out = 0
        self._stash = {}
        self._cursor = 0
        self._retry: list = []  # indices abandoned by crashed workers
        # lock order in this class: active -> cursor (_crashed nests
        # them that way; lockdep-checked under PADDLE_TPU_LOCKDEP)
        self._cursor_lock = _lockdep.lock("dataloader.cursor")
        self._restarts_left = int(max_restarts)
        self.restarts = 0  # observability: how many crashes were absorbed
        self._threads = []
        self._active = num_workers
        self._active_lock = _lockdep.lock("dataloader.active")
        for _ in range(num_workers):
            # start each thread as it is created: a crashed worker may
            # append its replacement to _threads concurrently, and a
            # start-them-all-afterwards loop would start that
            # (already-running) replacement a second time
            t = threading.Thread(target=self._worker, daemon=True)
            self._threads.append(t)
            t.start()

    def _next_index(self):
        with self._cursor_lock:
            if self._retry:
                return self._retry.pop()
            i = self._cursor
            self._cursor += 1
            return i if i < len(self._work) else None

    def _worker(self):
        i = None
        try:
            while True:
                i = self._next_index()
                if i is None:
                    break
                if _chaos.ACTIVE:
                    _chaos.fire("loader_worker")  # may kill this thread
                try:
                    batch = self._fetch(self._work[i])
                    payload = pickle.dumps((i, batch), protocol=5)
                except Exception as e:
                    # data-level error: surface at this batch position
                    # (the consumer raises it in order); the worker
                    # lives on and its restart budget is untouched
                    payload = pickle.dumps((i, e), protocol=5)
                t0 = time.perf_counter()
                if not self._ring.push(payload):
                    return  # ring closed by consumer shutdown
                # blocked push = backpressure: the consumer (train loop)
                # is the bottleneck, which is the healthy direction
                _M_PRODUCER_WAIT.observe((time.perf_counter() - t0) * 1e3)
                _M_QUEUE_DEPTH.set(len(self._ring))
                i = None
        except BaseException as e:  # worker DEATH (chaos kill, pickling
            self._crashed(i, e)     # failure, machinery bug)
            return
        self._finish()

    def _crashed(self, i, exc):
        """Restart a replacement worker within budget, else surface the
        error (in batch order) so the consumer raises instead of hanging."""
        with self._active_lock:
            if self._restarts_left > 0:
                self._restarts_left -= 1
                self.restarts += 1
                _M_RESTARTS.inc()
                if _journal.ACTIVE is not None:
                    _journal.ACTIVE.event(
                        "dataloader.worker_restart", batch_index=i,
                        error=f"{type(exc).__name__}: {exc}",
                        restarts_left=self._restarts_left)
                if i is not None:
                    with self._cursor_lock:
                        self._retry.append(i)  # replacement re-fetches it
                t = threading.Thread(target=self._worker, daemon=True)
                self._threads.append(t)
                t.start()  # replacement inherits this slot: _active unchanged
                return
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event(
                "dataloader.restart_budget_exhausted", batch_index=i,
                error=f"{type(exc).__name__}: {exc}")
        if i is not None:
            if not isinstance(exc, Exception):
                exc = RuntimeError(
                    f"DataLoader worker died ({exc!r}) and the restart "
                    "budget is exhausted")
            try:
                payload = pickle.dumps((i, exc), protocol=5)
            except Exception:
                payload = pickle.dumps(
                    (i, RuntimeError(f"DataLoader worker died: {exc!r} "
                                     "(original exception unpicklable)")),
                    protocol=5)
            self._ring.push(payload)
        self._finish()

    def _finish(self):
        with self._active_lock:
            self._active -= 1
            if self._active == 0:
                self._ring.close()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._next_out in self._stash:
                item = self._stash.pop(self._next_out)
                self._next_out += 1
                if isinstance(item, Exception):
                    raise item
                return item
            t0 = time.perf_counter()
            blob = self._ring.pop()
            # a long pop = the train loop starved waiting on input — the
            # number step-time attribution cares about most
            _M_CONSUMER_WAIT.observe((time.perf_counter() - t0) * 1e3)
            if blob is None:
                if self._next_out in self._stash:
                    continue
                raise StopIteration
            _M_QUEUE_DEPTH.set(len(self._ring))
            i, batch = pickle.loads(blob)
            self._stash[i] = batch  # restore deterministic batch order

    def shutdown(self, timeout=5.0):
        """Close the ring and JOIN the workers: an iterator abandoned
        mid-epoch (or one whose consumer raised) must not leak daemon
        threads still fetching batches."""
        self._ring.close()
        import time

        deadline = time.monotonic() + timeout
        for t in list(self._threads):  # snapshot: restarts may append
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class DevicePrefetcher:
    """Double-buffered async DEVICE feed: overlap host->device transfer
    with device compute.

    The host-side pipeline (``DataLoader`` workers, reader decorators)
    overlaps batch ASSEMBLY with compute; the last per-step serial cost
    is the feed ``device_put`` itself. This stage issues
    ``jax.device_put`` for batch N+1 on a feeder thread while the step
    consuming batch N runs — jax transfers are asynchronous, so by the
    time the train loop asks for N+1 the bytes are (usually) already in
    HBM. ``shardings`` places each transfer directly onto its committed
    device layout (see ``executor_feed_shardings``): a DP-sharded feed
    lands pre-sharded instead of being re-laid-out at dispatch.

    Fault contract (mirrors ``_Prefetcher``): an error ANYWHERE in the
    stage — the upstream iterator raising mid-prefetch, or the
    ``device_put`` itself failing — surfaces to the consumer in batch
    order (everything prefetched before it still arrives first), and
    ``shutdown()`` never hangs: the feeder thread is unblocked and
    joined even when the consumer abandons the iterator mid-epoch.

    ``depth`` is the lookahead (2 = classic double buffering). Keep it
    small: each in-flight batch holds device memory.
    """

    def __init__(self, source, shardings=None, depth=2):
        import queue

        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._shardings = shardings
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=self._feeder, args=(iter(source),), daemon=True)
        self._thread.start()

    # -- feeder thread -------------------------------------------------------
    def _transfer(self, batch):
        import jax

        sh = self._shardings

        def put(x, s=None):
            x = getattr(x, "_data", x)  # Tensor -> array
            return jax.device_put(x) if s is None else jax.device_put(x, s)

        def mismatch():
            # a shardings spec that cannot be matched to the batch shape
            # must FAIL, not quietly fall back to default placement —
            # the user asked for a layout and would otherwise never
            # learn they didn't get it
            return TypeError(
                f"DevicePrefetcher shardings of type "
                f"{type(sh).__name__} cannot be matched to a batch of "
                f"type {type(batch).__name__}: use a dict of "
                f"name->sharding for dict batches (executor_feed_"
                f"shardings), a sequence for tuple/list batches, or a "
                f"callable(batch)")

        if callable(sh):
            out = sh(batch)
        elif isinstance(batch, dict):
            if sh is not None and not isinstance(sh, dict):
                raise mismatch()
            m = sh or {}
            if m and not any(k in m for k in batch):
                # a shardings dict sharing NO key with the batch is a
                # naming mismatch (feed-name vs collate-key), not a
                # partial spec: every batch would silently take default
                # placement. (A superset spec — e.g. '@lr' from
                # executor_feed_shardings next to a {'x','y'} batch —
                # stays legal.)
                raise TypeError(
                    f"DevicePrefetcher shardings keys {sorted(m)} share "
                    f"no key with batch keys {sorted(batch)}: the "
                    "requested layout would be silently ignored")
            out = {k: put(v, m.get(k)) for k, v in batch.items()}
        elif isinstance(batch, (list, tuple)):
            if sh is not None and not isinstance(sh, (list, tuple)):
                raise mismatch()
            seq = list(sh) if sh is not None else []
            if len(seq) > len(batch):
                raise TypeError(
                    f"DevicePrefetcher got {len(seq)} shardings for a "
                    f"batch of {len(batch)} items: the extra entries "
                    "would be silently dropped")
            seq += [None] * (len(batch) - len(seq))
            out = [put(v, s) for v, s in zip(batch, seq)]
            out = tuple(out) if isinstance(batch, tuple) else out
        else:
            if isinstance(sh, (dict, list, tuple)):
                raise mismatch()
            out = put(batch, sh)
        _M_DEVICE_PUTS.inc()
        return out

    def _put(self, item):
        import queue

        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue  # consumer stalled; re-check for shutdown
        return False

    def _feeder(self, it):
        try:
            while not self._stop.is_set():
                try:
                    batch = next(it)
                except StopIteration:
                    break
                # device_put here ENQUEUES the transfer and returns;
                # the copy proceeds while the consumer's step computes
                if not self._put(("ok", self._transfer(batch))):
                    return
        except BaseException as e:  # upstream raise OR device_put failure:
            self._put(("err", e))   # surfaces in batch order
            return
        self._put(("end", None))

    # -- consumer side -------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        tag, val = self._q.get()
        _M_CONSUMER_WAIT.observe((time.perf_counter() - t0) * 1e3)
        if tag == "ok":
            return val
        self._done = True
        if tag == "err":
            if isinstance(val, Exception):
                raise val
            raise RuntimeError(f"device prefetch feeder died: {val!r}")
        raise StopIteration

    def shutdown(self, timeout=5.0):
        """Stop the feeder and join it. Safe to call repeatedly, from
        ``finally`` blocks, and mid-stream: the stop flag unblocks a
        feeder stuck on a full queue, and draining the queue unblocks
        one stuck in ``put``."""
        self._stop.set()
        self._done = True
        import queue

        try:  # drain so a feeder blocked in _put can observe _stop
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=timeout)


def prefetch_to_device(source, shardings=None, depth=2):
    """Generator wrapper over ``DevicePrefetcher`` with guaranteed
    cleanup: ``for batch in prefetch_to_device(loader): ...`` — the
    feeder thread is shut down when the loop ends, breaks, or raises."""
    pf = DevicePrefetcher(source, shardings=shardings, depth=depth)
    try:
        for batch in pf:
            yield batch
    finally:
        pf.shutdown()


def executor_feed_shardings(compiled):
    """Committed per-feed shardings of a compiled Executor entry, as a
    ``{feed_name: sharding_or_None}`` dict ready for
    ``DevicePrefetcher(shardings=...)`` — batches device_put through it
    land directly on the layout the executable expects (a DP entry's
    batch feeds arrive pre-sharded over the data mesh). None shardings
    mean default placement. Returns None when the entry is unknown.

    Fused entries (``run_steps``, ``compiled.steps=K``) carry shardings
    for the STACKED ``(K, batch, ...)`` arguments; the leading scan
    axis is stripped here so the returned dict applies to the
    individual per-step batches a loader yields (the batch axis is dim
    0 again). Prefetched per-step batches then enter via
    ``run_steps(feeds=[...])``, which stacks device arrays
    device-side."""
    names = getattr(compiled, "feed_names", None)
    if not names:
        return None
    sh = getattr(compiled, "feed_shardings", None)
    if sh is None:
        return {n: None for n in names}
    if getattr(compiled, "steps", None):
        from jax.sharding import NamedSharding, PartitionSpec

        def per_step(s):
            spec = getattr(s, "spec", None)
            if s is None or not spec or len(tuple(spec)) == 0:
                return s  # replicated (or unknown): unchanged
            return NamedSharding(s.mesh, PartitionSpec(*tuple(spec)[1:]))

        return {n: per_step(s) for n, s in zip(names, sh)}
    return dict(zip(names, sh))


class DataLoader:
    """ref: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 max_worker_restarts=2):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.max_worker_restarts = max_worker_restarts
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last) \
                if batch_size is not None else None
            self.batch_size = batch_size

    def _fetch_batch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)

    def __iter__(self):
        from ..core.tensor import Tensor

        def to_tensors(b):
            if not self.return_list:
                return b
            if isinstance(b, (list, tuple)):
                return [Tensor(np.asarray(x), _internal=False)
                        if isinstance(x, np.ndarray) else x for x in b]
            if isinstance(b, np.ndarray):
                return [Tensor(b, _internal=False)]
            return b

        if self._iterable_mode:
            for b in self._iter_iterable():
                yield to_tensors(b)
            return
        if self.batch_sampler is None:  # no batching: raw samples
            for i in range(len(self.dataset)):
                yield to_tensors(self.dataset[i])
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                with _trace.span("dataloader.next", workers=0):
                    b = self._fetch_batch(indices)
                yield to_tensors(b)
            return
        pf = _Prefetcher(self.batch_sampler, self._fetch_batch,
                         self.num_workers,
                         capacity=self.num_workers * self.prefetch_factor,
                         max_restarts=self.max_worker_restarts)
        try:
            it = iter(pf)
            while True:
                # span covers only the wait for the prefetched batch,
                # not the consumer's processing of it
                with _trace.span("dataloader.next",
                                 workers=self.num_workers):
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                yield to_tensors(b)
        finally:
            pf.shutdown()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)
