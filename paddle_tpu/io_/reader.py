"""Fluid-style reader decorators + DataFeeder.

Ref: python/paddle/reader/decorator.py (batch/shuffle/map_readers/
xmap_readers/...) and python/paddle/fluid/data_feeder.py.
"""
from __future__ import annotations

import itertools
import random as pyrandom
import threading

import numpy as np

__all__ = [
    "batch", "shuffle", "shuffle_stream", "buffered", "map_readers", "xmap_readers", "chain",
    "compose", "firstn", "cache", "DataFeeder", "prefetch_to_device",
]


def batch(reader, batch_size, drop_last=False):
    def gen():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return gen


def shuffle(reader, buf_size):
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                pyrandom.shuffle(buf)
                yield from buf
                buf = []
        pyrandom.shuffle(buf)
        yield from buf

    return gen


def buffered(reader, size):
    """Prefetch through the native ring buffer."""

    def gen():
        from ..runtime import RingBuffer
        import pickle

        ring = RingBuffer(size)

        def producer():
            try:
                for item in reader():
                    if not ring.push(pickle.dumps(item, protocol=5)):
                        return
            finally:
                ring.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            blob = ring.pop()
            if blob is None:
                break
            yield pickle.loads(blob)

    return gen


def map_readers(func, *readers):
    def gen():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return gen


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (ref: xmap_readers)."""

    def gen():
        import queue

        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        END = object()

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(END)

        def work():
            while True:
                got = in_q.get()
                if got is END:
                    out_q.put(END)
                    return
                i, item = got
                out_q.put((i, mapper(item)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        done = 0
        stash, nxt = {}, 0
        while done < process_num:
            got = out_q.get()
            if got is END:
                done += 1
                continue
            i, item = got
            if not order:
                yield item
            else:
                stash[i] = item
                while nxt in stash:
                    yield stash.pop(nxt)
                    nxt += 1
        if order:
            for i in sorted(stash):
                yield stash[i]

    return gen


def chain(*readers):
    def gen():
        for r in readers:
            yield from r()

    return gen


def compose(*readers, check_alignment=True):
    def gen():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                out.extend(it if isinstance(it, tuple) else (it,))
            yield tuple(out)

    return gen


def firstn(reader, n):
    def gen():
        return itertools.islice(reader(), n)

    return gen


def cache(reader):
    data = []
    filled = [False]

    def gen():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        yield from data

    return gen


class DataFeeder:
    """Convert reader items into an Executor feed dict (ref: data_feeder.py)."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = [v if isinstance(v, str) else v.name
                           for v in feed_list]

    def feed(self, iterable):
        cols = list(zip(*iterable))
        out = {}
        for name, col in zip(self.feed_names, cols):
            out[name] = np.stack([np.asarray(c) for c in col])
        return out


def prefetch_to_device(reader, shardings=None, depth=2):
    """Reader decorator over the double-buffered device feed
    (``io_.dataloader.DevicePrefetcher``): ``jax.device_put`` for batch
    N+1 is issued while the consumer computes on batch N, onto the
    committed shardings when given (``executor_feed_shardings`` of a
    compiled entry). The feeder thread is shut down when the consumer
    finishes, breaks, or raises — reader errors surface in batch
    order."""

    def impl():
        from .dataloader import prefetch_to_device as _stage

        return _stage(reader(), shardings=shardings, depth=depth)

    return impl


def shuffle_stream(reader, buf_size=1024, seed=0):
    """Streaming shuffle backed by the native reservoir
    (runtime/cc PtShufflePool): a producer thread fills the pool while
    the consumer draws uniformly random samples, so shuffling overlaps
    with upstream decode work (the python ``shuffle`` drains its buffer
    in bursts instead). Samples are pickled through the pool."""
    import pickle

    from ..runtime import ShufflePool

    def impl():
        pool = ShufflePool(capacity=buf_size, seed=seed,
                           min_fill=max(buf_size // 2, 1))
        err = []
        done = []

        def producer():
            try:
                for sample in reader():
                    if not pool.push(pickle.dumps(sample)):
                        return          # consumer closed the pool
            except BaseException as e:
                err.append(e)
            finally:
                done.append(True)
                pool.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                try:
                    # short waits so a slow producer is a retry, not EOF
                    blob = pool.pop(timeout_ms=1000)
                except TimeoutError:
                    if done and not len(pool):
                        break
                    continue
                if blob is None:
                    break
                yield pickle.loads(blob)
        finally:
            # unblock a producer stuck in push if the consumer bails
            pool.close()
            t.join(timeout=5.0)
        if err:
            raise err[0]

    return impl
