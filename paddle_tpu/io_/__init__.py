"""paddle_tpu.io — data pipeline (datasets, samplers, DataLoader, readers).

Mirrors ``paddle.io`` + fluid's reader stack; prefetch is backed by the
native C++ ring buffer in paddle_tpu/runtime.
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn, default_convert_fn  # noqa: F401
from . import reader  # noqa: F401
from .reader import DataFeeder  # noqa: F401
