"""paddle_tpu.io — data pipeline (datasets, samplers, DataLoader, readers).

Mirrors ``paddle.io`` + fluid's reader stack; prefetch is backed by the
native C++ ring buffer in paddle_tpu/runtime.
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler,
)
from .dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, default_convert_fn, DevicePrefetcher,
    prefetch_to_device, executor_feed_shardings,
)
from . import reader  # noqa: F401
from .reader import DataFeeder  # noqa: F401
