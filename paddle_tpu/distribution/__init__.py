"""paddle_tpu.distribution — probability distributions.

Ref: python/paddle/fluid/layers/distributions.py (Uniform/Normal/
Categorical sample, log_prob, kl_divergence, entropy) and the
paddle.distribution 2.0 API. TPU-native: sampling uses the framework's
threaded PRNG keys (core/random.py) so draws inside a jitted step are
reproducible and trace-safe.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as prandom
from ..core.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical", "Bernoulli",
           "MultivariateNormalDiag", "kl_divergence"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def _wrap(a):
    return Tensor(a, _internal=True)


class Distribution:
    """ref: distributions.py Distribution base."""

    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low).astype(jnp.float32)
        self.high = _arr(high).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(prandom.next_key(), shape, jnp.float32)
        return _wrap(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.float32)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _wrap(jnp.log(self.high - self.low))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        z = jax.random.normal(prandom.next_key(), shape, jnp.float32)
        return _wrap(self.loc + z * self.scale)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.float32)
        var = self.scale ** 2
        return _wrap(-((v - self.loc) ** 2) / (2 * var) -
                     jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _wrap(0.5 + 0.5 * math.log(2 * math.pi) +
                     jnp.log(self.scale))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits).astype(jnp.float32)

    @property
    def _logp(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        return _wrap(jax.random.categorical(prandom.next_key(), self.logits,
                                            shape=tuple(shape) +
                                            self.logits.shape[:-1]))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        return _wrap(jnp.take_along_axis(self._logp, v[..., None],
                                         axis=-1)[..., 0])

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        p = jax.nn.softmax(self.logits, axis=-1)
        return _wrap(-jnp.sum(p * self._logp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs_ = jnp.clip(_arr(probs).astype(jnp.float32),
                                   1e-7, 1 - 1e-7)
            self.logits_ = jnp.log(self.probs_) - jnp.log1p(-self.probs_)
        else:
            self.logits_ = _arr(logits).astype(jnp.float32)
            self.probs_ = jax.nn.sigmoid(self.logits_)

    def sample(self, shape=()):
        u = jax.random.uniform(prandom.next_key(),
                               tuple(shape) + self.probs_.shape)
        return _wrap((u < self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.float32)
        return _wrap(v * jnp.log(self.probs_) +
                     (1 - v) * jnp.log1p(-self.probs_))

    def entropy(self):
        p = self.probs_
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (ref: distributions.py
    MultivariateNormalDiag): ``scale`` is the diagonal matrix; only its
    diagonal participates."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        sc = _arr(scale).astype(jnp.float32)
        self.diag = jnp.diagonal(sc, axis1=-2, axis2=-1) if sc.ndim >= 2 \
            else sc

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.diag.shape)
        z = jax.random.normal(prandom.next_key(), shape, jnp.float32)
        return _wrap(self.loc + z * self.diag)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.float32)
        k = self.loc.shape[-1]
        quad = jnp.sum(((v - self.loc) / self.diag) ** 2, axis=-1)
        logdet = jnp.sum(jnp.log(self.diag ** 2), axis=-1)
        return _wrap(-0.5 * (quad + logdet + k * math.log(2 * math.pi)))

    def entropy(self):
        k = self.loc.shape[-1]
        logdet = jnp.sum(jnp.log(self.diag ** 2), axis=-1)
        return _wrap(0.5 * (k * (1 + math.log(2 * math.pi)) + logdet))


def kl_divergence(p, q):
    """ref: distributions.py kl_divergence (closed forms per pair)."""
    if isinstance(p, MultivariateNormalDiag) and \
            isinstance(q, MultivariateNormalDiag):
        var_p, var_q = p.diag ** 2, q.diag ** 2
        k = p.loc.shape[-1]
        return _wrap(0.5 * (
            jnp.sum(var_p / var_q, axis=-1) +
            jnp.sum((q.loc - p.loc) ** 2 / var_q, axis=-1) - k +
            jnp.sum(jnp.log(var_q), axis=-1) -
            jnp.sum(jnp.log(var_p), axis=-1)))
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        out = jnp.log((q.high - q.low) / (p.high - p.low))
        ok = (q.low <= p.low) & (p.high <= q.high)
        return _wrap(jnp.where(ok, out, jnp.inf))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        pp = jax.nn.softmax(p.logits, axis=-1)
        return _wrap(jnp.sum(pp * (p._logp - q._logp), axis=-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a, b = p.probs_, q.probs_
        return _wrap(a * (jnp.log(a) - jnp.log(b)) +
                     (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
