"""paddle_tpu.serving: production serving — paged KV cache, continuous
batching, per-request observability.

The TPU-native serving layer the reference covers with
``paddle/fluid/inference`` + the decode operators: where
``inference.Predictor`` replays one saved program per call, this
subsystem serves *many concurrent generation requests* through shared
compiled steps —

- ``kv_cache.PagedKVCache``: fixed-size pages from one preallocated,
  donation-recycled device pool; host-side free list; per-sequence
  page tables; strict alloc==free accounting.
- ``ops/pallas/paged_attention.paged_decode_attention``: the ragged
  paged decode kernel (one kernel for the whole mixed batch, K/V
  gathered through page tables via scalar prefetch).
- ``scheduler.Scheduler``: continuous batching — token-budget
  admission, prefill/decode interleaving, preemption by page pressure
  with arrival-order requeue, deterministic under an injectable clock.
- ``engine.ServeEngine``: the serve loop tying them together, with
  ``serving.*`` metrics (queue depth, TTFT/TPOT/e2e histograms),
  lifecycle trace spans, and journal ``request`` records.

``tools/serve_bench.py`` drives a synthetic Poisson trace through the
engine and reports p50/p99 TTFT/TPOT and tokens/s.

The multi-replica layer lives in ``serving.fleet``: a load-aware
``Router`` over a ``ReplicaPool`` of engines (in-process or worker
processes), per-tenant fairness + rate limits, SLO-driven
``Autoscaler``, and elastic replica relaunch — see that package's
docstring.
"""
from .kv_cache import (CachePressureError, PageAllocationError,
                       PagedKVCache, write_tokens)
from .scheduler import (Batch, ManualClock, Request, Scheduler,
                        QUEUED, RUNNING, PREEMPTED, FINISHED, CANCELLED)
from .engine import ServeEngine, TinyLM
from . import fleet  # noqa: F401  (serving.fleet.Router et al.)

__all__ = [
    "PagedKVCache", "PageAllocationError", "CachePressureError",
    "write_tokens",
    "Scheduler", "Request", "Batch", "ManualClock",
    "QUEUED", "RUNNING", "PREEMPTED", "FINISHED", "CANCELLED",
    "ServeEngine", "TinyLM", "fleet",
]
