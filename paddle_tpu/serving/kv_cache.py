"""Paged KV-cache: fixed-size pages from one preallocated device pool.

The serving memory model (vLLM's PagedAttention, arXiv 2604.15464's
TPU shape): instead of one contiguous ``(B, max_len, H, D)`` cache —
which reserves worst-case length for every request and fragments the
batch — the pool is ``num_pages`` fixed-size pages, and each sequence
holds an ordered *page table* of the pages its tokens live in. Free
pages are a **host-side free list**: allocation and release are pure
Python bookkeeping (no device traffic), and the device pools change
only through the compiled decode/prefill steps, which **donate** the
pool buffers — the executable updates pages in place in HBM, one
resident copy across the run (``tools/perf_gate.py`` asserts the
``input_output_alias`` on the compiled HLO).

Page 0 is the **null page**: never allocated, it absorbs the K/V
writes of padded batch lanes (a bucketed decode step always writes B
lanes; parking dead lanes on a real page would corrupt a live
sequence) and backs the clamped tail entries of padded page tables.

Accounting is strict: every page is either in the free list or in
exactly one sequence's table (``verify()``), so ``alloc == free``
balance after any request teardown — including a chaos-killed one —
is a testable invariant, not a hope.
"""
from __future__ import annotations

import time as _time

import numpy as np

from ..obs import lockdep as _lockdep
from ..obs import metrics as _metrics
from ..resilience.policy import TransientError

__all__ = ["PagedKVCache", "PageAllocationError", "CachePressureError",
           "write_tokens"]

_M_USED = _metrics.gauge("serving.kv.used_pages")
_M_FREE = _metrics.gauge("serving.kv.free_pages")
_M_ALLOCS = _metrics.counter("serving.kv.page_allocs")
_M_FREES = _metrics.counter("serving.kv.page_frees")


class PageAllocationError(RuntimeError):
    """The pool cannot satisfy an allocation (free list exhausted)."""


class CachePressureError(TransientError):
    """Page pressure the scheduler may relieve by preempting a victim —
    a ``TransientError`` so the engine's ``RecoveryPolicy`` retry path
    (``resilience.policy.retry_call``) drives relief with the same
    bounded-retry machinery every other recoverable fault uses."""


class PagedKVCache:
    """Host-side allocator + device-side pools for paged K/V.

    >>> cache = PagedKVCache(num_pages=64, page_size=16, num_heads=4,
    ...                      head_dim=32)
    >>> cache.alloc("req1", 40)        # 3 pages for a 40-token prompt
    >>> cache.extend("req1")           # decode: page 3 only at 49->...
    >>> cache.free("req1")

    Device pools ``k_pages``/``v_pages`` are ``(num_layers, num_pages,
    page_size, num_heads, head_dim)`` jax arrays, created lazily on
    first touch so constructing an allocator never forces backend init.
    The pools are *replaced* (not mutated) by the engine after each
    donated step — the allocator only hands out page ids.
    """

    NULL_PAGE = 0

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 num_layers=1, dtype="float32", max_seq_len=None):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_layers = int(num_layers)
        self.dtype = dtype
        capacity = (self.num_pages - 1) * self.page_size
        self.max_seq_len = int(max_seq_len) if max_seq_len else capacity
        if self.max_seq_len > capacity:
            # advertising more than the pool holds would defeat the
            # engine's at-the-door oversize rejection: an accepted
            # request could still never be admitted (permanent FIFO-
            # head stall for everything queued behind it)
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds pool capacity "
                f"{capacity} tokens ({self.num_pages - 1} usable pages "
                f"x {self.page_size})")
        # lowest-id-first allocation keeps traces deterministic
        self._free = sorted(range(1, self.num_pages))
        self._tables = {}    # seq_id -> [page ids, in order]
        self._lengths = {}   # seq_id -> tokens stored
        # page-second attribution (obs.usage): integrate pages-held x
        # time per sequence, in INTEGER nanoseconds so per-tenant sums
        # are exact (float accumulation is not associative). The clock
        # is injectable (Scheduler aligns it with its own) so the
        # integrals are ManualClock-exact in tests; stamps live and die
        # with the page table, so closure (no open stamp without a
        # table) is part of verify().
        self.clock = None             # None -> time.monotonic
        self._page_open = {}          # seq_id -> [last_ns, pages, acc_ns]
        self._page_ns = {}            # seq_id -> closed integral (int ns)
        self._seq_allocs = 0          # alloc() calls granted a table
        self._seq_frees = 0           # free() calls that released one
        # leaf of the serving order (engine.step -> scheduler -> cache):
        # nothing may be acquired while this is held
        self._lock = _lockdep.lock("serving.kv_cache")
        self._k = None
        self._v = None
        self._update_gauges()

    # -- device pools --------------------------------------------------------
    @property
    def k_pages(self):
        self._ensure_pools()
        return self._k

    @property
    def v_pages(self):
        self._ensure_pools()
        return self._v

    def _ensure_pools(self):
        if self._k is None:
            import jax.numpy as jnp

            shape = (self.num_layers, self.num_pages, self.page_size,
                     self.num_heads, self.head_dim)
            self._k = jnp.zeros(shape, dtype=self.dtype)
            self._v = jnp.zeros(shape, dtype=self.dtype)

    def set_pools(self, k_pages, v_pages):
        """Install the pools a donated step returned. The old buffers
        were consumed by donation — holding them would be a
        use-after-free; this is the only sanctioned replacement path."""
        self._k, self._v = k_pages, v_pages

    # -- allocation ----------------------------------------------------------
    def pages_needed(self, n_tokens):
        return -(-int(n_tokens) // self.page_size)

    def can_alloc(self, n_tokens):
        with self._lock:
            return self.pages_needed(n_tokens) <= len(self._free)

    def alloc(self, seq_id, n_tokens):
        """Allocate pages for ``n_tokens`` (a prompt). All-or-nothing:
        on pressure nothing is held. Returns the page ids granted."""
        n_tokens = int(n_tokens)
        if n_tokens > self.max_seq_len:
            raise ValueError(
                f"sequence of {n_tokens} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        need = self.pages_needed(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise KeyError(f"sequence {seq_id!r} already allocated")
            if need > len(self._free):
                raise PageAllocationError(
                    f"need {need} pages for {n_tokens} tokens, "
                    f"{len(self._free)} free")
            pages = [self._free.pop(0) for _ in range(need)]
            self._tables[seq_id] = pages
            self._lengths[seq_id] = n_tokens
            self._page_open[seq_id] = [self._stamp_ns(), need, 0]
            self._seq_allocs += 1
            _M_ALLOCS.inc(need)
            self._update_gauges_locked()
            return list(pages)

    def extend(self, seq_id, n_tokens=1):
        """Grow a sequence by ``n_tokens`` (decode appends). Allocates
        a new page only when the last page fills; all-or-nothing under
        pressure (the sequence keeps its old length). Returns the list
        of newly granted pages (usually empty)."""
        n_tokens = int(n_tokens)
        with self._lock:
            if seq_id not in self._tables:
                raise KeyError(f"unknown sequence {seq_id!r}")
            cur = self._lengths[seq_id]
            if cur + n_tokens > self.max_seq_len:
                raise ValueError(
                    f"sequence {seq_id!r} would exceed max_seq_len "
                    f"{self.max_seq_len}")
            need = self.pages_needed(cur + n_tokens) - \
                self.pages_needed(cur)
            if need > len(self._free):
                raise PageAllocationError(
                    f"extend({seq_id!r}) needs {need} page(s), "
                    f"{len(self._free)} free")
            new = [self._free.pop(0) for _ in range(need)]
            self._tables[seq_id].extend(new)
            self._lengths[seq_id] = cur + n_tokens
            if new:
                # page count changed: close the integral's interval at
                # the OLD count and restamp at the new one
                st = self._page_open[seq_id]
                now = self._stamp_ns()
                st[2] += (now - st[0]) * st[1]
                st[0] = now
                st[1] += len(new)
                _M_ALLOCS.inc(len(new))
            self._update_gauges_locked()
            return new

    def free(self, seq_id):
        """Release every page a sequence holds (finish, preemption, or
        a chaos-killed request — the teardown path is the same).
        Returns the number of pages released; unknown ids are a no-op
        (teardown must be idempotent under crash-retry)."""
        with self._lock:
            pages = self._tables.pop(seq_id, None)
            self._lengths.pop(seq_id, None)
            if pages is not None:
                # close the page-second integral; a re-admission after
                # preemption re-allocs under the same seq_id, so closed
                # integrals ACCUMULATE across incarnations
                st = self._page_open.pop(seq_id)
                st[2] += (self._stamp_ns() - st[0]) * st[1]
                self._page_ns[seq_id] = \
                    self._page_ns.get(seq_id, 0) + st[2]
                self._seq_frees += 1
            if not pages:
                return 0
            self._free.extend(pages)
            self._free.sort()
            _M_FREES.inc(len(pages))
            self._update_gauges_locked()
            return len(pages)

    # -- page-second attribution ---------------------------------------------
    def _stamp_ns(self):
        """Now, in integer nanoseconds on the injected clock. Called
        under the leaf lock; the clock is a plain callable (monotonic
        or a ManualClock read), never blocking."""
        clk = self.clock
        return int(round((clk() if clk is not None else
                          _time.monotonic()) * 1e9))

    def page_usage(self):
        """Pull-only snapshot of the page-second integrals: per-seq
        CLOSED integrals (int ns; accumulated across preempt/re-admit
        incarnations), currently-OPEN page counts, and the alloc/free
        closure counters. Nothing here mutates the integrals."""
        with self._lock:
            return {
                "closed_ns": dict(self._page_ns),
                "open": {sid: st[1]
                         for sid, st in self._page_open.items()},
                "seq_allocs": self._seq_allocs,
                "seq_frees": self._seq_frees,
            }

    def closed_page_ns(self, seq_id):
        """Closed page-second integral for one sequence (int ns)."""
        with self._lock:
            return self._page_ns.get(seq_id, 0)

    @property
    def page_bytes(self):
        """HBM bytes one page pins across BOTH pools and all layers —
        the page-MB-s chargeback conversion factor."""
        itemsize = np.dtype(self.dtype).itemsize
        return (2 * self.num_layers * self.page_size * self.num_heads
                * self.head_dim * itemsize)

    # -- introspection (locked like the mutators: the engine loop is
    # single-threaded, but submit/cancel may come from other threads
    # and a torn read here would KeyError the whole serve step) -------------
    def page_table(self, seq_id):
        with self._lock:
            return list(self._tables[seq_id])

    def length(self, seq_id):
        with self._lock:
            return self._lengths[seq_id]

    def sequences(self):
        with self._lock:
            return sorted(self._tables)

    def padded_page_tables(self, seq_ids, width=None):
        """``(len(seq_ids), width)`` int32 table for the kernel, tail
        entries parked on the null page. ``width`` defaults to the
        pool-wide maximum (``max_seq_len`` pages); callers batching
        short contexts pass the batch's own bucket so the kernel grid
        stays O(context)."""
        width = width or self.table_width
        out = np.full((len(seq_ids), width), self.NULL_PAGE, np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                pages = self._tables[sid]
                if len(pages) > width:
                    raise ValueError(
                        f"sequence {sid!r} holds {len(pages)} pages > "
                        f"table width {width}")
                out[i, :len(pages)] = pages
        return out

    @property
    def table_width(self):
        return self.pages_needed(self.max_seq_len)

    def write_slots(self, seq_ids):
        """``(page_id, offset)`` arrays addressing each sequence's NEXT
        token slot (position == current length). The caller must have
        ``extend``-ed first so the page exists."""
        pages = np.empty(len(seq_ids), np.int32)
        offs = np.empty(len(seq_ids), np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                pos = self._lengths[sid] - 1
                pages[i] = self._tables[sid][pos // self.page_size]
                offs[i] = pos % self.page_size
        return pages, offs

    def stats(self):
        """Pool occupancy + fragmentation: ``utilization`` is live
        tokens over the capacity of the pages holding them (1.0 = no
        internal fragmentation); ``fragmentation`` its complement."""
        with self._lock:
            used = self.num_pages - 1 - len(self._free)
            tokens = sum(self._lengths.values())
            cap = used * self.page_size
            return {
                "num_pages": self.num_pages,
                "page_size": self.page_size,
                "used_pages": used,
                "free_pages": len(self._free),
                "sequences": len(self._tables),
                "tokens": tokens,
                "utilization": (tokens / cap) if cap else 1.0,
                "fragmentation": (1.0 - tokens / cap) if cap else 0.0,
            }

    def verify(self):
        """Every page (except null) is free or owned exactly once."""
        with self._lock:
            owned = [p for t in self._tables.values() for p in t]
            seen = set(owned)
            assert len(owned) == len(seen), "page owned twice"
            assert not (seen & set(self._free)), "page both free+owned"
            assert self.NULL_PAGE not in seen, "null page allocated"
            total = 1 + len(self._free) + len(owned)
            assert total == self.num_pages, \
                f"page leak: {self.num_pages - total} unaccounted"
            # page-second closure: an open stamp exists iff the page
            # table does (alloc==free discipline for the integrals)
            assert set(self._page_open) == set(self._tables), \
                "page-second stamp out of sync with page tables"
            assert self._seq_allocs - self._seq_frees == \
                len(self._tables), "page-second alloc/free counter leak"
        return True

    def _update_gauges_locked(self):
        _M_USED.set(self.num_pages - 1 - len(self._free))
        _M_FREE.set(len(self._free))

    def _update_gauges(self):
        with self._lock:
            self._update_gauges_locked()


def write_tokens(k_pages, v_pages, k_new, v_new, page_ids, offsets,
                 layer=0):
    """Functional scatter of one new token per lane into the pools:
    ``k_new``/``v_new`` are ``(B, H, D)``, ``page_ids``/``offsets``
    ``(B,)``. Pure (jit-able); the engine's compiled decode step calls
    this with the pools donated, so XLA aliases the update in place —
    padded lanes target the null page by construction."""
    k_pages = k_pages.at[layer, page_ids, offsets].set(k_new)
    v_pages = v_pages.at[layer, page_ids, offsets].set(v_new)
    return k_pages, v_pages
