"""Continuous-batching scheduler: admission, interleaving, preemption.

The serving control plane (the role Orca/vLLM's scheduler plays, and
the scenario template of the Gemma-on-TPU serving comparison, arXiv
2605.25645): requests arrive at any time, and every engine step serves
a *mixed* batch — new requests' prefills interleaved with in-flight
requests' decodes — rather than waiting for a static batch to drain.

Rules (each one is pinned exactly by tests/test_serving.py):

- **Admission under a token budget.** A step may process at most
  ``token_budget`` tokens: each in-flight decode costs 1, a prefill
  costs its prompt length. Decodes are budgeted first (in-flight
  requests never starve behind new arrivals), then queued requests
  admit in strict arrival order while budget AND KV pages last —
  FIFO admission is the no-starvation guarantee.
- **Preemption by page pressure.** When a decode needs a page and the
  pool is dry, the *youngest* running request (latest admission) is
  preempted: its pages are freed, its generated-so-far tokens fold
  into its prompt, and it requeues by its ORIGINAL arrival time — so
  a preempted request loses its cache, not its place. The oldest
  running request is never chosen (guaranteed forward progress).
- **Deterministic under an injectable clock.** Every timestamp comes
  from ``clock()`` (default ``time.monotonic``); tests drive a
  ``ManualClock`` so traces — admission order, preemption step,
  timestamps — are exact, not approximate.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

from ..obs import lockdep as _lockdep
from ..obs import metrics as _metrics
from .kv_cache import CachePressureError, PageAllocationError

__all__ = ["Request", "Batch", "Scheduler", "ManualClock",
           "QUEUED", "RUNNING", "PREEMPTED", "FINISHED", "CANCELLED"]

QUEUED, RUNNING, PREEMPTED, FINISHED, CANCELLED = (
    "QUEUED", "RUNNING", "PREEMPTED", "FINISHED", "CANCELLED")

_M_QUEUE = _metrics.gauge("serving.queue_depth")
_M_RUNNING = _metrics.gauge("serving.running")
_M_ADMITTED = _metrics.counter("serving.requests_admitted")
_M_PREEMPTED = _metrics.counter("serving.requests_preempted")
_M_REJECTED = _metrics.counter("serving.requests_rejected")

_rid_counter = itertools.count()


class ManualClock:
    """Deterministic test clock: ``clock()`` reads, ``advance`` moves."""

    def __init__(self, start=0.0):
        self.now = float(start)

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += float(dt)
        return self.now


@dataclass
class Request:
    """One generation request and its full lifecycle record."""

    prompt: list                     # token ids
    max_new_tokens: int = 16
    rid: str = None
    eos_id: int = None
    # billing identity: every request is owned by exactly one tenant
    # (None = the router's implicit "default" tenant); obs.usage charges
    # device-seconds and KV page-seconds to this key
    tenant: str = None
    state: str = QUEUED
    # lifecycle timestamps (scheduler clock)
    arrival_t: float = None
    admit_t: float = None
    first_token_t: float = None
    finish_t: float = None
    # progress
    generated: list = field(default_factory=list)
    preemptions: int = 0
    pages_peak: int = 0
    # request-scoped tracing (obs.reqtrace): the trace id minted at
    # Router.submit rides dispatch into this replica's Request; the
    # preempt/resume stamp pairs are what preemption-loss attribution
    # is computed from (every preempt_ts[i] pairs with resume_ts[i],
    # a final unpaired preempt pairs with finish_t)
    trace: str = None
    preempt_ts: list = field(default_factory=list)
    resume_ts: list = field(default_factory=list)

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(self.max_new_tokens)
        if self.max_new_tokens < 1:
            # the prefill unconditionally emits the first token, so a
            # zero-token request would still generate one — reject it
            raise ValueError("max_new_tokens must be >= 1")
        if self.rid is None:
            self.rid = f"req-{next(_rid_counter)}"

    @property
    def context(self):
        """prompt + generated: what a (re-)prefill must encode."""
        return self.prompt + self.generated

    @property
    def done(self):
        return len(self.generated) >= self.max_new_tokens or (
            self.eos_id is not None and self.generated
            and self.generated[-1] == self.eos_id)


@dataclass
class Batch:
    """One step's work: prefills (newly admitted / resumed) + decodes."""

    prefills: list = field(default_factory=list)
    decodes: list = field(default_factory=list)

    @property
    def tokens(self):
        return sum(len(r.context) for r in self.prefills) + \
            len(self.decodes)

    def __bool__(self):
        return bool(self.prefills or self.decodes)


class Scheduler:
    def __init__(self, cache, token_budget=256, max_batch=None,
                 clock=None):
        self.cache = cache
        self.token_budget = int(token_budget)
        self.max_batch = int(max_batch) if max_batch else None
        self.clock = clock if clock is not None else time.monotonic
        # page-second attribution (obs.usage) integrates pages x time
        # from cache stamps; those stamps must tick on the SAME clock
        # as the request lifecycle or the integrals drift off the
        # ManualClock-exact timeline tests pin
        if getattr(cache, "clock", None) is None:
            cache.clock = self.clock
        self._queue = []      # QUEUED/PREEMPTED, kept in arrival order
        self._running = []    # RUNNING, in admission order
        self.preemptions = 0
        # one reentrant lock over _queue/_running: submit()/cancel()
        # may arrive from other threads while the engine thread is
        # inside schedule() — an unlocked head pop racing a remove()
        # would silently discard (and permanently lose) a request.
        # Lock order is scheduler -> cache, everywhere (the journal's
        # lock nests under the scheduler's too — record_request fires
        # inside schedule(); journal is a leaf, it never calls back).
        # lockdep-instrumented under PADDLE_TPU_LOCKDEP, plain RLock
        # otherwise.
        self._lock = _lockdep.rlock("serving.scheduler")

    # -- intake --------------------------------------------------------------
    def submit(self, request):
        with self._lock:
            if request.arrival_t is None:
                request.arrival_t = self.clock()
            request.state = QUEUED
            self._enqueue(request)
            return request

    def _enqueue(self, request):
        """Insert keeping arrival order (a preempted request re-enters
        at its original arrival position — it lost its cache, not its
        place in line)."""
        i = len(self._queue)
        while i > 0 and self._queue[i - 1].arrival_t > request.arrival_t:
            i -= 1
        self._queue.insert(i, request)
        _M_QUEUE.set(len(self._queue))

    # -- the per-step decision -----------------------------------------------
    def schedule(self):
        """Build this step's Batch: decodes first (1 token each), then
        admissions in arrival order while token budget, batch slots,
        and KV pages remain."""
        with self._lock:
            batch = Batch()
            budget = self.token_budget
            for r in self._running:
                if budget <= 0:
                    break
                if self.max_batch and \
                        len(batch.decodes) >= self.max_batch:
                    break
                batch.decodes.append(r)
                budget -= 1
            while self._queue and budget > 0:
                if self.max_batch and len(batch.decodes) + \
                        len(batch.prefills) >= self.max_batch:
                    break
                nxt = self._queue[0]
                cost = len(nxt.context)
                if cost > self.cache.max_seq_len:
                    # scheduler-direct submission of an unservable prompt
                    # (ServeEngine.submit rejects these at the door):
                    # reject it terminally instead of letting cache.alloc
                    # ValueError out of schedule() — which would kill the
                    # serve loop and strand the popped request stateless.
                    # A terminal path must stay observable like every
                    # other: counter + journal request record
                    self._queue.pop(0)
                    nxt.state = CANCELLED
                    nxt.finish_t = self.clock()
                    _M_REJECTED.inc()
                    from ..obs import journal as _journal

                    if _journal.ACTIVE is not None:
                        _journal.ACTIVE.record_request(
                            rid=nxt.rid, state=CANCELLED,
                            arrival_t=nxt.arrival_t,
                            finish_t=nxt.finish_t,
                            prompt_tokens=len(nxt.prompt),
                            output_tokens=len(nxt.generated),
                            preemptions=nxt.preemptions,
                            tenant=nxt.tenant,
                            rejected="context exceeds max_seq_len")
                    continue
                if cost > budget:
                    break  # strict FIFO: never skip ahead of the blocked head
                # +1 token of headroom: don't admit a prompt that exactly
                # fills its pages into an instantly-stalling state — but
                # ONLY when the request will actually grow past `cost`
                # (a preemption-resumed context already at its deepest,
                # prompt + max_new - 1, needs no headroom; demanding it
                # would refuse a capacity-boundary request forever).
                # Best effort — the page is checked, not reserved, so a
                # later admission in this same loop may still consume it
                # (preemption then relieves the stall as usual)
                worst = len(nxt.prompt) + nxt.max_new_tokens - 1
                if not self.cache.can_alloc(cost + 1 if worst > cost
                                            else cost):
                    break
                self._queue.pop(0)
                self.cache.alloc(nxt.rid, cost)
                nxt.state = RUNNING
                resumed = nxt.admit_t is not None
                if not resumed:           # a preemption resume keeps the
                    nxt.admit_t = self.clock()  # original admission time
                else:
                    # close the open preempt interval: preemption-loss
                    # attribution pairs resume_ts[i] with preempt_ts[i]
                    nxt.resume_ts.append(self.clock())
                nxt.pages_peak = max(nxt.pages_peak,
                                     len(self.cache.page_table(nxt.rid)))
                self._running.append(nxt)
                batch.prefills.append(nxt)
                budget -= cost
                _M_ADMITTED.inc()
                from ..obs import journal as _journal

                if _journal.ACTIVE is not None:
                    # reqtrace lifecycle edge: scheduler admission (the
                    # journal lock nests under the scheduler's, leaf)
                    _journal.ACTIVE.event(
                        "req.admit", rid=nxt.rid, at=nxt.resume_ts[-1]
                        if resumed else nxt.admit_t, resumed=resumed)
            _M_QUEUE.set(len(self._queue))
            _M_RUNNING.set(len(self._running))
            return batch

    # -- growth + pressure ---------------------------------------------------
    def extend(self, request, n_tokens=1):
        """Grow ``request`` by ``n_tokens`` in the KV cache; page
        pressure surfaces as ``CachePressureError`` (retryable — the
        engine relieves it via ``preempt_for``)."""
        with self._lock:
            try:
                new = self.cache.extend(request.rid, n_tokens)
            except PageAllocationError as e:
                raise CachePressureError(str(e)) from e
            request.pages_peak = max(
                request.pages_peak,
                len(self.cache.page_table(request.rid)))
            return new

    def preempt_for(self, request):
        """Relieve page pressure for ``request``: preempt the YOUNGEST
        running request other than the requester — and never the
        oldest (the oldest always makes forward progress, which is
        what rules out preemption livelock). Returns the victim, or
        None when no one else is preemptable — the engine then
        self-preempts the requester (it IS the youngest)."""
        with self._lock:
            if not self._running:
                return None
            victims = [r for r in self._running[1:] if r is not request]
            if not victims:
                return None
            victim = victims[-1]
            self._preempt(victim)
            return victim

    def preempt(self, victim):
        """Preempt ``victim`` directly (the engine's last resort when
        relief for the victim itself ran out of budget)."""
        self._preempt(victim)

    def _preempt(self, victim):
        with self._lock:
            return self._preempt_locked(victim)

    def _preempt_locked(self, victim):
        self.cache.free(victim.rid)
        self._running.remove(victim)
        victim.state = PREEMPTED
        victim.preemptions += 1
        victim.preempt_ts.append(self.clock())
        self.preemptions += 1
        _M_PREEMPTED.inc()
        self._enqueue(victim)
        _M_RUNNING.set(len(self._running))
        from ..obs import journal as _journal

        if _journal.ACTIVE is not None:
            # reqtrace lifecycle edge: preemption start (the matching
            # resume is the req.admit event with resumed=True)
            _journal.ACTIVE.event("req.preempt", rid=victim.rid,
                                  at=victim.preempt_ts[-1],
                                  preemptions=victim.preemptions)

    # -- teardown ------------------------------------------------------------
    def finish(self, request, state=FINISHED):
        """Release a request's pages and drop it from the running set
        (normal completion, cancellation, or a chaos-killed request —
        one teardown path, so alloc==free holds in every exit)."""
        with self._lock:
            self.cache.free(request.rid)
            if request in self._running:
                self._running.remove(request)
            if request in self._queue:
                self._queue.remove(request)
            request.state = state
            request.finish_t = self.clock()
            _M_QUEUE.set(len(self._queue))
            _M_RUNNING.set(len(self._running))

    # -- introspection -------------------------------------------------------
    @property
    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    @property
    def running(self):
        with self._lock:
            return list(self._running)

    @property
    def idle(self):
        with self._lock:
            return not self._queue and not self._running
