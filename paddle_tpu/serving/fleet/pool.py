"""ReplicaPool: launch, watch, drain, and relaunch serve replicas.

The fleet data plane under the :class:`~.router.Router`. One
:class:`ReplicaSpec` describes a replica (model, KV pool, token
budget, shared AOT cache, journal root); the pool materializes N of
them in one of two modes:

- ``mode="local"`` — in-process :class:`~..engine.ServeEngine`s on a
  shared (injectable) clock: the deterministic substrate for dispatch-
  trace tests and ``tools/serve_bench.py --replicas N``.
- ``mode="process"`` — one ``serving.fleet.worker`` subprocess per
  replica, speaking newline-JSON over stdin/stdout, heartbeating like
  a PR-8 gang worker (``PADDLE_TPU_HEARTBEAT_FILE``), journaling
  per-rank under ``<run_dir>/rank_NN`` (PR-13), exporting its own
  ``/metrics`` endpoint, and hydrating every prefill/decode bucket
  from the SHARED AOT executable cache (``runtime.aot``) — so a
  relaunch or scale-up pays deserialize, not XLA.

Replica health rides the heartbeat/watchdog pattern: a dead process is
reaped, a wedged one (stale heartbeat) is SIGKILLed, and either way
the pool hands the router the casualty's in-flight requests to requeue
and relaunches the replica under the
:class:`~...resilience.elastic.ReplicaSupervisor`'s per-replica
restart budget + seeded backoff. Scale-down goes through ``drain()``:
the replica stops accepting, finishes its in-flight decodes, and only
then retires — never killed mid-decode.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ...obs import journal as _journal
from ...obs import lockdep as _lockdep
from ...resilience.elastic import HEARTBEAT_ENV, ATTEMPT_ENV, \
    ReplicaSupervisor

__all__ = ["ReplicaSpec", "LocalReplica", "ProcessReplica",
           "ReplicaPool",
           "STARTING", "READY", "DRAINING", "DEAD", "RETIRED"]

STARTING, READY, DRAINING, DEAD, RETIRED = (
    "STARTING", "READY", "DRAINING", "DEAD", "RETIRED")


def _journal_event(kind, **fields):
    if _journal.ACTIVE is not None:
        _journal.ACTIVE.event(kind, **fields)


@dataclass
class ReplicaSpec:
    """Everything needed to build one replica (and rebuild it warm)."""

    vocab_size: int = 32
    num_heads: int = 2
    head_dim: int = 8
    seed: int = 0
    pages: int = 64
    page_size: int = 8
    max_seq_len: int = None
    token_budget: int = 256
    max_batch: int = 8          # warm() bound = deepest decode bucket
    warm: bool = True
    aot_cache_dir: str = None   # shared executable cache (process mode)
    run_dir: str = None         # fleet journal root (rank_NN per replica)
    metrics_port: int = None    # None = no exporter; 0 = ephemeral
    env: dict = field(default_factory=dict)
    env_for_replica: object = None   # (replica_id, attempt) -> dict
    hang_timeout_s: float = 60.0
    startup_timeout_s: float = 180.0

    @property
    def effective_max_seq_len(self):
        cap = (int(self.pages) - 1) * int(self.page_size)
        return min(int(self.max_seq_len), cap) if self.max_seq_len \
            else cap

    def build_engine(self, replica_id, clock=None):
        """One in-process replica: model + paged pool + scheduler +
        engine, all from this spec (the worker process runs the same
        construction — one recipe, two substrates)."""
        from ..engine import ServeEngine, TinyLM
        from ..kv_cache import PagedKVCache
        from ..scheduler import Scheduler

        model = TinyLM(vocab_size=self.vocab_size,
                       num_heads=self.num_heads,
                       head_dim=self.head_dim, seed=self.seed)
        cache = PagedKVCache(self.pages, self.page_size, self.num_heads,
                             self.head_dim,
                             max_seq_len=self.effective_max_seq_len)
        sched = Scheduler(cache, token_budget=self.token_budget,
                          clock=clock if clock is not None
                          else time.monotonic)
        return ServeEngine(model, cache, scheduler=sched,
                           aot_cache_dir=self.aot_cache_dir,
                           replica_id=replica_id)

    def worker_argv(self, replica_id):
        return [
            sys.executable, "-m", "paddle_tpu.serving.fleet.worker",
            "--replica-id", str(replica_id),
            "--vocab-size", str(self.vocab_size),
            "--num-heads", str(self.num_heads),
            "--head-dim", str(self.head_dim),
            "--seed", str(self.seed),
            "--pages", str(self.pages),
            "--page-size", str(self.page_size),
            "--max-seq-len", str(self.effective_max_seq_len),
            "--token-budget", str(self.token_budget),
            "--max-batch", str(self.max_batch),
            "--metrics-port", str(-1 if self.metrics_port is None
                                  else self.metrics_port),
        ] + (["--warm"] if self.warm else [])


class _BaseReplica:
    """The router-side replica handle: a submit/poll surface plus the
    outstanding-token ledger the dispatch decision reads."""

    def __init__(self, replica_id, attempt=0):
        self.replica_id = int(replica_id)
        self.attempt = int(attempt)
        self.state = STARTING
        self.last_failure = None      # "exit" | "hung" once DEAD
        self._ledger = {}             # rid -> FleetRequest in flight

    @property
    def accepting(self):
        return self.state == READY

    @property
    def draining(self):
        return self.state == DRAINING

    @property
    def outstanding_tokens(self):
        return sum(r.cost for r in self._ledger.values())

    @property
    def inflight_count(self):
        return len(self._ledger)

    def take_inflight(self):
        """Strand-recovery: the requests this replica still owed, in
        arrival order; the ledger empties (they belong to the router's
        requeue now)."""
        out = sorted(self._ledger.values(),
                     key=lambda r: (r.arrival_t, r.rid))
        self._ledger.clear()
        return out

    def drain(self):
        if self.state == READY:
            self.state = DRAINING

    # subclass surface -------------------------------------------------------
    def submit(self, req):  # pragma: no cover - abstract
        raise NotImplementedError

    def poll(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def health(self, now):
        """None when healthy, else the failure kind ("exit"/"hung")."""
        return None

    def kill(self):
        self.state = DEAD
        self.last_failure = "exit"

    def close(self):
        if self.state not in (DEAD,):
            self.state = RETIRED


class LocalReplica(_BaseReplica):
    """In-process replica: a ServeEngine stepped by ``pool.pump()``."""

    def __init__(self, spec, replica_id, clock=None, attempt=0):
        super().__init__(replica_id, attempt)
        self.spec = spec
        self.engine = spec.build_engine(replica_id, clock=clock)
        if spec.warm and spec.aot_cache_dir:
            self.engine.warm(max_batch=spec.max_batch)
        self._done_mark = 0
        self._crashed = False
        self.state = READY

    def submit(self, req):
        try:
            self.engine.submit(req.prompt,
                               max_new_tokens=req.max_new_tokens,
                               rid=req.rid, eos_id=req.eos_id,
                               arrival_t=req.arrival_t,
                               trace=req.trace_id, tenant=req.tenant)
        except ValueError:
            # the router pre-validates with the same rules, so this is
            # a spec drift bug — surface it, don't strand the request
            raise
        self._ledger[req.rid] = req

    def pump(self, steps=1):
        if self._crashed or self.state in (DEAD, RETIRED):
            return 0
        n = 0
        for _ in range(steps):
            if self.engine.scheduler.idle:
                break
            if not self.engine.step():
                break
            n += 1
        return n

    def poll(self):
        out = []
        fin = self.engine.finished
        while self._done_mark < len(fin):
            r = fin[self._done_mark]
            self._done_mark += 1
            if r.rid not in self._ledger:
                continue
            self._ledger.pop(r.rid, None)
            out.append({
                "rid": r.rid, "state": r.state,
                "tokens": list(r.generated),
                "arrival_t": r.arrival_t, "admit_t": r.admit_t,
                "first_token_t": r.first_token_t,
                "finish_t": r.finish_t,
                "preemptions": r.preemptions,
            })
        return out

    def kill(self):
        """Simulated machine loss (tests): the engine stops serving
        but — like a real dead machine — the pool only notices at the
        next health sweep, which requeues the stranded ledger."""
        self._crashed = True

    def close(self):
        # mirror the process-mode worker's before-bye emission so a
        # local-mode run dir bills the same way: final per-tenant
        # engine truth into the shared journal. A killed local
        # replica skips it — machine loss loses its meter, as billed.
        if not self._crashed and self.state not in (DEAD,) \
                and _journal.ACTIVE is not None:
            from ...obs import usage as _usage

            _journal_event("tenant.usage",
                           **_usage.engine_tenant_usage(self.engine))
        super().close()

    def health(self, now=None):
        return "exit" if self._crashed else None


class ProcessReplica(_BaseReplica):
    """One ``serving.fleet.worker`` subprocess, newline-JSON protocol:

    parent -> worker: ``{"op": "submit"|"cancel"|"drain"|"stats"|"stop",
    ...}``; worker -> parent: ``{"t": "ready"|"done"|"rejected"|
    "drained"|"stats", ...}``. A reader thread drains stdout so the
    worker never blocks on a full pipe; ``poll()`` consumes the
    buffered events on the router thread."""

    def __init__(self, spec, replica_id, hb_path, env, attempt=0):
        super().__init__(replica_id, attempt)
        self.spec = spec
        self.hb_path = hb_path
        self.metrics_url = None
        self.pid = None
        self.spawned_at = time.monotonic()
        self._events = deque()
        # guards _events between the reader thread (producer) and the
        # router thread (consumer). Leaf of the fleet control-plane
        # order router -> pool -> replica: the reader thread holds it
        # only around deque ops, never while journaling or touching
        # the pool.
        self._lock = _lockdep.lock("fleet.replica_events")
        self._drained = False
        try:  # a stale beacon from the previous incarnation must not
            os.remove(hb_path)  # read as liveness
        except OSError:
            pass
        self.proc = subprocess.Popen(
            spec.worker_argv(replica_id), env=env,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            bufsize=1)
        self.pid = self.proc.pid
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"pt-replica-{replica_id}",
                                        daemon=True)
        self._reader.start()

    def _read_loop(self):
        try:
            for line in self.proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # stray print from a library
                with self._lock:
                    self._events.append(ev)
        except Exception:
            pass

    def _mark_ready(self, ev):
        port = ev.get("metrics_port")
        if port:
            self.metrics_url = f"http://127.0.0.1:{port}/metrics"
        if self.state == STARTING:
            self.state = READY

    def scan_ready(self):
        """Non-blocking readiness check: consume the worker's buffered
        ``ready`` event if it arrived, promoting STARTING -> READY.
        Returns the event, or None. (The pool's health sweep calls this
        so a background-warming relaunch joins service on its own
        schedule — the router thread never blocks on a warm.)"""
        with self._lock:
            for ev in list(self._events):
                if ev.get("t") == "ready":
                    self._events.remove(ev)
                    self._mark_ready(ev)
                    return ev
        return None

    def wait_ready(self, timeout_s=None):
        """Block until the worker's ``ready`` line (post-warm, exporter
        bound). Raises on worker death or timeout."""
        timeout_s = self.spec.startup_timeout_s if timeout_s is None \
            else timeout_s
        deadline = time.monotonic() + float(timeout_s)
        while True:
            ev = self.scan_ready()
            if ev is not None:
                return ev
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.replica_id} died before ready "
                    f"(exit {self.proc.returncode})")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {self.replica_id} not ready in "
                    f"{timeout_s}s")
            time.sleep(0.02)

    def _send(self, msg):
        try:
            self.proc.stdin.write(json.dumps(msg) + "\n")
            self.proc.stdin.flush()
            return True
        except (OSError, ValueError):
            return False  # dead pipe: the health sweep reaps it

    def submit(self, req):
        self._ledger[req.rid] = req
        self._send({"op": "submit", "rid": req.rid,
                    "prompt": req.prompt,
                    "max_new_tokens": req.max_new_tokens,
                    "eos_id": req.eos_id,
                    "arrival_t": req.arrival_t,
                    "trace": req.trace_id,
                    "tenant": req.tenant})

    def drain(self):
        super().drain()
        self._send({"op": "drain"})

    def poll(self):
        out = []
        with self._lock:
            evs, self._events = list(self._events), deque()
        for ev in evs:
            t = ev.get("t")
            if t == "ready":
                # a background relaunch's ready line can land between
                # the health sweep's scan_ready and this poll; it must
                # promote here too, not vanish with the batch — dropped,
                # the replica would sit STARTING forever (fresh
                # heartbeat, so never flagged unhealthy either)
                self._mark_ready(ev)
            elif t == "done":
                if ev.get("rid") in self._ledger:
                    self._ledger.pop(ev["rid"], None)
                    out.append(ev)
            elif t == "rejected":
                self._ledger.pop(ev.get("rid"), None)
            elif t == "drained":
                self._drained = True
            # anything else ("stats"/"bye") has no parent-side reader:
            # poll() is the stream's terminal consumer and drops it
        return out

    def health(self, now=None):
        if self.state not in (READY, DRAINING, STARTING):
            return None
        rc = self.proc.poll()
        if rc is not None:
            # a drain-complete worker exiting 0 is a clean retirement,
            # not a failure
            if self._drained and rc == 0 and not self._ledger:
                return None
            return "exit"
        if self.state == STARTING:
            # the worker beats once at boot and then warms WITHOUT
            # beating (serve-loop beats start post-ready), so heartbeat
            # age says nothing here: the whole STARTING window gets the
            # startup grace, not the steady-state hang timeout — else a
            # slow warm is SIGKILLed mid-hydration and relaunched in a
            # loop until the supervisor budget burns out
            if time.monotonic() - self.spawned_at > \
                    self.spec.startup_timeout_s:
                return "hung"
            return None
        try:
            age = time.time() - os.path.getmtime(self.hb_path)
        except OSError:
            if time.monotonic() - self.spawned_at > \
                    self.spec.startup_timeout_s:
                return "hung"
            return None
        if age > self.spec.hang_timeout_s:
            return "hung"
        return None

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=10)
        except OSError:
            pass
        super().kill()

    def stop(self, timeout_s=15.0):
        """Graceful stop: the worker flushes its journal and exits."""
        if self.proc.poll() is None:
            self._send({"op": "stop"})
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        self.close()

    def close(self):
        super().close()
        for f in (self.proc.stdin, self.proc.stdout):
            try:
                f.close()
            except Exception:
                pass


class ReplicaPool:
    """N replicas of one :class:`ReplicaSpec` plus their lifecycle."""

    def __init__(self, spec, replicas=1, mode="local", clock=None,
                 supervisor=None, max_replicas=None):
        if mode not in ("local", "process"):
            raise ValueError(f"mode must be local|process, got {mode!r}")
        self.spec = spec
        self.mode = mode
        self.clock = clock
        # process replicas timestamp on the WALL clock (worker and
        # router are different processes; monotonic clocks don't
        # compare across them), local ones on whatever the tests inject
        self.default_clock = (clock if clock is not None else
                              (time.time if mode == "process"
                               else time.monotonic))
        self.supervisor = supervisor or ReplicaSupervisor()
        self.max_replicas = max_replicas
        self.replicas = []        # live (READY/DRAINING/STARTING)
        self.retired = []
        # replica_id -> {"attempt", "not_before"}: relaunches waiting
        # out their supervisor backoff (spawned by the health sweep)
        self._pending = {}
        self._next_id = 0
        self._hb_dir = None
        if mode == "process":
            self._hb_dir = tempfile.mkdtemp(prefix="pt_fleet_hb_")
        for _ in range(int(replicas)):
            self.scale_up()

    # -- spawning ------------------------------------------------------------
    def _worker_env(self, replica_id, attempt):
        env = dict(os.environ)
        env.update(self.spec.env or {})
        if attempt > 0:
            # inherited chaos is an attempt-0 drill config: a relaunch
            # that re-fired the same kill would never heal (the
            # at_step-keyed gang injectors solve this with global
            # steps; serve steps restart at 0 every incarnation)
            env["PADDLE_TPU_CHAOS"] = ""
        if self.spec.aot_cache_dir:
            from ...runtime import aot as _aot

            env.update(_aot.shared_cache_env(self.spec.aot_cache_dir))
        if self.spec.run_dir:
            env["PADDLE_TPU_RUN_DIR"] = os.path.join(
                self.spec.run_dir, _journal.rank_subdir(replica_id))
            env[_journal.RANK_ENV] = str(replica_id)
        env[HEARTBEAT_ENV] = self._hb_path(replica_id)
        env[ATTEMPT_ENV] = str(attempt)
        # the worker imports paddle_tpu from THIS checkout
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        if self.spec.env_for_replica is not None:
            env.update(self.spec.env_for_replica(replica_id, attempt)
                       or {})
        return env

    def _hb_path(self, replica_id):
        return os.path.join(self._hb_dir or tempfile.gettempdir(),
                            f"hb_replica_{replica_id}.json")

    def _spawn(self, replica_id, attempt, wait=True):
        if self.mode == "local":
            rep = LocalReplica(self.spec, replica_id, clock=self.clock,
                               attempt=attempt)
        else:
            rep = ProcessReplica(self.spec, replica_id,
                                 self._hb_path(replica_id),
                                 self._worker_env(replica_id, attempt),
                                 attempt=attempt)
            if wait:
                rep.wait_ready()
        _journal_event("fleet.replica_spawn", replica=replica_id,
                       attempt=attempt, mode=self.mode,
                       pid=getattr(rep, "pid", None))
        return rep

    def headroom(self):
        """Remaining replica slots under ``max_replicas`` (None =
        unbounded). Live replicas in any non-terminal state — STARTING
        and DRAINING still hold host capacity — AND backoff-pending
        relaunches count: a pending relaunch WILL respawn
        unconditionally, so ignoring it would let a scale-up overshoot
        the cap."""
        if self.max_replicas is None:
            return None
        live = sum(1 for r in self.replicas
                   if r.state not in (DEAD, RETIRED))
        return max(0, self.max_replicas - live - len(self._pending))

    def at_capacity(self):
        return self.headroom() == 0

    def scale_up(self, wait=True):
        """Launch one more replica. ``wait=False`` returns a STARTING
        process replica that warms in the background (the health
        sweep promotes it) — the autoscaler's mode, so an "up" never
        stalls the dispatch loop for a whole boot+warm."""
        if self.at_capacity():
            raise RuntimeError(
                f"pool already at max_replicas={self.max_replicas}")
        rep = self._spawn(self._next_id, attempt=0, wait=wait)
        self._next_id += 1
        self.replicas.append(rep)
        return rep

    def relaunch(self, rep):
        """Replace a DEAD replica (supervisor budget + backoff first —
        raises ``ElasticBudgetError`` when a replica flaps past its
        budget). The new incarnation keeps the replica id, so journals
        and SLO labels read as one replica's history. Nothing here
        blocks the router thread: the backoff is NOT slept (the pool
        records a not-before time on its clock and a later health
        sweep does the spawn), and process-mode spawns return a
        STARTING replica that warms in the BACKGROUND, promoted to
        READY when its ``ready`` line lands — a relaunch blocking the
        dispatch loop for a backoff or a warm would stall the healthy
        fleet, exactly what replica isolation exists to prevent.
        Returns the fresh replica, or None when the spawn is deferred
        behind its backoff."""
        kind = "hang" if rep.last_failure == "hung" else "crash"
        delay = self.supervisor.note_failure(rep.replica_id, kind=kind,
                                             defer=True)
        if delay > 0:
            self.replicas = [r for r in self.replicas if r is not rep]
            self._pending[rep.replica_id] = {
                "attempt": rep.attempt + 1,
                "not_before": self.default_clock() + delay}
            return None
        fresh = self._spawn(rep.replica_id, attempt=rep.attempt + 1,
                            wait=False)
        self.replicas = [fresh if r is rep else r
                         for r in self.replicas]
        return fresh

    def _spawn_pending(self, now):
        """Launch every backoff-deferred relaunch whose not-before time
        has passed (pool clock)."""
        for rid in sorted(self._pending):
            p = self._pending[rid]
            if now >= p["not_before"]:
                del self._pending[rid]
                fresh = self._spawn(rid, attempt=p["attempt"],
                                    wait=False)
                self.replicas.append(fresh)

    # -- health --------------------------------------------------------------
    def check_health(self, now=None):
        """Sweep for newly failed replicas: reap exits, SIGKILL stale-
        heartbeat hangs. Marks them DEAD and returns
        ``[(replica, reason)]`` — the router requeues their in-flight
        requests before asking for a relaunch. Also launches relaunches
        whose supervisor backoff just expired."""
        self._spawn_pending(self.default_clock() if now is None
                            else now)
        out = []
        for rep in list(self.replicas):
            if rep.state == STARTING and \
                    isinstance(rep, ProcessReplica):
                rep.scan_ready()   # background warm done -> READY
            if rep.state not in (READY, DRAINING, STARTING):
                continue
            reason = rep.health(now)
            if reason is None:
                continue
            if reason == "hung":
                rep.kill()  # SIGTERM can't help a wedged serve loop
            rep.state = DEAD
            rep.last_failure = reason
            _journal_event("fleet.replica_dead", replica=rep.replica_id,
                           reason=reason,
                           inflight=rep.inflight_count)
            out.append((rep, reason))
        return out

    # -- router surface ------------------------------------------------------
    def active(self):
        return [r for r in self.replicas if r.accepting]

    def topology(self):
        """The fleet's live shape as plain data: one row per replica
        (current AND retired) with id, state, incarnation (the
        supervisor attempt — a relaunch bumps it), load, and the last
        failure reason. The /statusz fleet table (``obs.export``)
        renders exactly this."""
        rows = []
        for rep in list(self.replicas) + list(self.retired):
            rows.append({
                "replica": rep.replica_id, "state": rep.state,
                "incarnation": rep.attempt,
                "outstanding_tokens": rep.outstanding_tokens,
                "inflight": rep.inflight_count,
                "mode": ("process" if isinstance(rep, ProcessReplica)
                         else "local"),
                "last_failure": getattr(rep, "last_failure", None),
            })
        rows.sort(key=lambda r: (r["replica"], r["incarnation"]))
        return rows

    def local_engines(self):
        return [r.engine for r in self.replicas
                if isinstance(r, LocalReplica)
                and r.state in (READY, DRAINING)]

    def scrape_targets(self):
        return [r.metrics_url for r in self.replicas
                if isinstance(r, ProcessReplica) and r.metrics_url
                and r.state in (READY, DRAINING)]

    def pump(self, steps=1):
        """Step every live in-process engine (process replicas pump
        themselves)."""
        n = 0
        for rep in self.replicas:
            if isinstance(rep, LocalReplica):
                n += rep.pump(steps)
        return n

    def retire(self, rep):
        """Remove a drained (or dead-while-draining) replica from
        service."""
        if isinstance(rep, ProcessReplica):
            rep.stop()
        else:
            rep.close()
        if rep in self.replicas:
            self.replicas.remove(rep)
        self.retired.append(rep)
        _journal_event("fleet.replica_retired", replica=rep.replica_id)

    def shutdown(self):
        self._pending.clear()
        for rep in list(self.replicas):
            if isinstance(rep, ProcessReplica):
                rep.stop()
            else:
                rep.close()
        self.replicas = []
        if self._hb_dir:
            import shutil

            shutil.rmtree(self._hb_dir, ignore_errors=True)
            self._hb_dir = None
