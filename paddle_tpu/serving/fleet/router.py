"""Load-aware request router over a pool of serve replicas.

The fleet control plane (ROADMAP item 5; the dispatch layer the Ragged
Paged Attention trajectory, arXiv 2604.15464, assumes above the
per-replica kernel, serving the replica-fleet scenario of the
Gemma-on-TPU comparison, arXiv 2605.25645). One ``Router`` fronts N
replicas — in-process ``ServeEngine``s or worker processes
(``pool.ReplicaPool``) — and decides, per request, WHICH replica
serves it:

- **Least-outstanding-tokens dispatch.** A request's load estimate is
  ``len(prompt) + max_new_tokens`` (the tokens the replica will hold
  and produce); it goes to the accepting replica with the smallest
  outstanding total, ties broken by LOWEST replica id — so dispatch
  traces are deterministic, not "whichever polled first".
- **Per-tenant fairness + rate limits**, layered ON TOP of each
  replica's token-budget scheduler: every tenant has an arrival-order
  queue; a token-bucket rate limit (injectable clock) holds a tenant's
  head back without blocking anyone else, and among rate-eligible
  tenants the one with the smallest served-tokens/weight deficit
  dispatches next (weighted deficit round-robin). One tenant flooding
  the fleet cannot starve another; within a tenant, arrival order is
  strict.
- **Requeue without losing your place.** When a replica dies (or is
  killed by the pool's heartbeat watchdog) its in-flight requests
  requeue by ORIGINAL arrival time, keeping their first-dispatch
  ``admit_t`` — the router-level mirror of the scheduler's preemption
  rule ("a preempted request loses its cache, not its place"). Decode
  is deterministic, so a re-dispatched request still finishes
  token-for-token identical to the single-engine oracle.
- **Admission control at the door.** Oversize / never-schedulable
  requests are rejected with the SAME semantics as
  ``ServeEngine.submit`` (vocab range, ``max_seq_len``,
  ``token_budget``) — an unservable request must not gridlock a
  replica's FIFO head.

Deterministic under an injectable clock: every timestamp and rate
decision comes from ``clock()`` (default ``time.monotonic``), so tests
drive a ``ManualClock`` and assert EXACT dispatch traces. Router truth
lands in ``fleet.router.*`` metrics, ``obs.export.router_lines``
gauges (scraped == ``stats()`` bitwise), and — when a run journal is
active — ``router.*`` events that ``tools/run_report.py`` /
``tools/fleet_report.py`` summarize.

**Concurrency contract** (checked by ``analysis.concurrency`` +
``obs.lockdep``): the Router itself is single-threaded — one thread
owns ``dispatch()``/``pump()``/``poll()``; it holds NO lock of its
own. The fleet lock order is **router → pool → replica**: the only
lock on this control plane is each ``ProcessReplica``'s events lock
(class ``fleet.replica_events``), a leaf taken briefly by the router
thread (consume) and the replica's reader thread (produce). Never
journal, scrape, sleep, or call back into the pool while holding it.
"""
from __future__ import annotations

import itertools
import time
from collections import deque

from ...obs import journal as _journal
from ...obs import metrics as _metrics
from ..scheduler import CANCELLED, FINISHED, QUEUED

__all__ = ["FleetRequest", "TenantPolicy", "TokenBucket", "Router",
           "DISPATCHED", "REJECTED"]

DISPATCHED = "DISPATCHED"
REJECTED = "REJECTED"

# process-wide counters live under serving.router.* — the
# fleet_router_* exposition namespace belongs to obs.export.router_lines
# (per-Router truth); sharing one family name would put a counter and a
# gauge with different values under the same Prometheus family, which a
# real server rejects as an invalid exposition
_M_DISPATCHED = _metrics.counter("serving.router.dispatched")
_M_REQUEUED = _metrics.counter("serving.router.requeued")
_M_REJECTED = _metrics.counter("serving.router.rejected")
_M_COMPLETED = _metrics.counter("serving.router.completed")
_M_QUEUE = _metrics.gauge("serving.router.queue_depth")
_M_REPLICAS = _metrics.gauge("serving.router.replicas")
_M_SCALE_UP = _metrics.counter("serving.router.scale_ups")
_M_SCALE_DOWN = _metrics.counter("serving.router.scale_downs")

_frid_counter = itertools.count()
_trace_counter = itertools.count()


class FleetRequest:
    """One routed request: the router-level lifecycle record (the
    per-replica ``scheduler.Request`` is the replica's own view)."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id", "tenant",
                 "state", "arrival_t", "admit_t", "first_token_t",
                 "finish_t", "replica_id", "tokens", "requeues",
                 "preemptions", "dispatches", "trace_id", "requeue_ts",
                 "rate_hold_t", "rate_wait")

    def __init__(self, prompt, max_new_tokens=16, rid=None, eos_id=None,
                 tenant="default", arrival_t=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.rid = rid if rid is not None else f"fr-{next(_frid_counter)}"
        self.eos_id = eos_id
        self.tenant = str(tenant)
        self.state = QUEUED
        self.arrival_t = arrival_t
        self.admit_t = None          # first dispatch; requeue keeps it
        self.first_token_t = None
        self.finish_t = None
        self.replica_id = None       # current / last replica
        self.tokens = []             # generated tokens once finished
        self.requeues = 0
        self.preemptions = 0         # in-replica preemptions, reported back
        self.dispatches = []         # [(t, replica_id)] — the trace
        # request-scoped tracing (obs.reqtrace): one trace id per
        # routed request, minted here and propagated through dispatch
        # into the replica's engine Request (both pool modes)
        self.trace_id = f"tr-{next(_trace_counter):06d}-{self.rid}"
        self.requeue_ts = []         # [t] — when a dead replica stranded it
        # tenant-bucket wait accounting: rate_hold_t is the open
        # hold's start (the head was rate-blocked at that clock),
        # rate_wait accumulates closed holds in seconds
        self.rate_hold_t = None
        self.rate_wait = 0.0

    @property
    def cost(self):
        """Outstanding-token load estimate: tokens the replica must
        hold + produce (prompt prefill + full decode budget)."""
        return len(self.prompt) + self.max_new_tokens

    def __repr__(self):
        return (f"FleetRequest({self.rid!r}, tenant={self.tenant!r}, "
                f"state={self.state}, replica={self.replica_id})")


class TenantPolicy:
    """Per-tenant dispatch policy: ``weight`` scales the fairness share
    (a weight-2 tenant gets 2x the tokens of a weight-1 tenant under
    contention); ``rate``/``burst`` bound its token throughput via a
    :class:`TokenBucket` (None = unlimited)."""

    def __init__(self, weight=1.0, rate=None, burst=None):
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError("tenant weight must be > 0")
        self.rate = None if rate is None else float(rate)
        self.burst = burst

    def bucket(self, now):
        if self.rate is None:
            return None
        burst = self.burst if self.burst is not None else self.rate
        return TokenBucket(self.rate, burst, now=now)


class TokenBucket:
    """Deterministic token bucket on an injectable clock: starts full
    at ``burst`` tokens, refills at ``rate`` tokens/s."""

    def __init__(self, rate, burst, now=0.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self.level = float(burst)
        self._last = float(now)

    def _refill(self, now):
        if now > self._last:
            self.level = min(self.burst,
                             self.level + (now - self._last) * self.rate)
            self._last = now

    def peek(self, n, now):
        self._refill(now)
        return self.level >= float(n)

    def take(self, n, now):
        self._refill(now)
        if self.level < float(n):
            return False
        self.level -= float(n)
        return True


class Router:
    """SLO-aware dispatch over a :class:`~.pool.ReplicaPool`.

    >>> pool = ReplicaPool(ReplicaSpec(...), replicas=2)
    >>> router = Router(pool)
    >>> r = router.submit([3, 1, 4], max_new_tokens=8)
    >>> router.run_until_drained()
    >>> r.tokens

    The driving loop is explicit (``dispatch``/``poll``/
    ``check_replicas`` — or the ``step()``/``run_until_drained()``
    conveniences) so tests can interleave clock advances with single
    decisions and assert exact traces.
    """

    def __init__(self, pool, clock=None, tenants=None,
                 max_outstanding_per_replica=None, autoscaler=None,
                 autoscale_interval_s=1.0, slo=None):
        self.pool = pool
        self.clock = clock if clock is not None \
            else getattr(pool, "default_clock", time.monotonic)
        self.tenants = dict(tenants or {})
        self.max_outstanding = (None if max_outstanding_per_replica
                                is None
                                else int(max_outstanding_per_replica))
        self.autoscaler = autoscaler
        # step() observes the autoscaler at most once per interval: an
        # observation costs a full exposition build — for process pools
        # one HTTP scrape per replica — which a per-step loop would pay
        # hundreds of times per cooldown window for guaranteed no-ops
        self.autoscale_interval_s = float(autoscale_interval_s)
        self._next_autoscale_t = None
        # live SLO engine (obs.slo.SLOEvaluator): fed on the SAME
        # throttled tick from the SAME exposition the autoscaler
        # consumes — attaching SLO evaluation adds ZERO scrapes. With
        # slo=None the serve loop never touches obs.slo/obs.timeseries
        # (the zero-overhead poison test pins it).
        self.slo = slo
        # bounded plain-data trail of scale/requeue decisions for the
        # live /statusz pane (the journal stays the durable record)
        self.recent_events = deque(maxlen=64)
        self._queues = {}      # tenant -> [FleetRequest] arrival order
        self._buckets = {}     # tenant -> ((rate, burst), TokenBucket|None)
        self._default_policy = TenantPolicy()
        self._served = {}      # tenant -> tokens dispatched
        # per-tenant outcome counters (obs.usage.router_tenant_usage
        # reads these pull-only for the chargeback/fairness rollup)
        self._rejected_by_tenant = {}    # tenant -> rejects
        self._rate_holds_by_tenant = {}  # tenant -> hold episodes
        self._requeued_by_tenant = {}    # tenant -> requeues
        self._inflight = {}    # rid -> FleetRequest
        self.completed = []    # FINISHED/CANCELLED FleetRequests
        self.trace = []        # [{"t", "rid", "replica", "tenant"}]
        self.dispatched = 0
        self.requeued = 0
        self.rejected = 0
        self.scale_ups = 0
        self.scale_downs = 0
        _M_REPLICAS.set(len(pool.active()))

    # -- intake --------------------------------------------------------------
    def _policy(self, tenant):
        return self.tenants.get(tenant) or self._default_policy

    def submit(self, prompt, max_new_tokens=16, rid=None, eos_id=None,
               tenant="default", arrival_t=None):
        """Queue one request. Raises ``ValueError`` with the single-
        engine ``ServeEngine.submit`` semantics for requests no replica
        could ever serve (and counts them as rejected)."""
        req = FleetRequest(prompt, max_new_tokens=max_new_tokens,
                           rid=rid, eos_id=eos_id, tenant=tenant,
                           arrival_t=arrival_t)
        if req.arrival_t is None:
            req.arrival_t = self.clock()
        spec = self.pool.spec
        try:
            if req.rid in self._inflight or any(
                    q.rid == req.rid for qs in self._queues.values()
                    for q in qs):
                # a second live 'x' would silently overwrite the first
                # in the in-flight book, stranding one request forever
                # and stamping the other with the wrong tokens
                raise ValueError(
                    f"rid {req.rid!r} is already queued or in flight")
            if not req.prompt:
                raise ValueError("empty prompt")
            if req.max_new_tokens < 1:
                raise ValueError("max_new_tokens must be >= 1")
            if any(not 0 <= t < spec.vocab_size for t in req.prompt):
                raise ValueError("prompt token out of vocab range")
            worst = len(req.prompt) + req.max_new_tokens - 1
            if worst > spec.effective_max_seq_len:
                raise ValueError(
                    f"request needs up to {worst} cached tokens > "
                    f"max_seq_len {spec.effective_max_seq_len}")
            if worst > spec.token_budget:
                raise ValueError(
                    f"request may re-prefill up to {worst} tokens > "
                    f"token_budget {spec.token_budget}: it could never "
                    "be (re-)admitted on any replica")
            pol = self._policy(req.tenant)
            if pol.rate is not None:
                burst = pol.burst if pol.burst is not None else pol.rate
                if req.cost > burst:
                    # the bucket caps at burst: a costlier request
                    # would sit at the tenant head FOREVER — the same
                    # silent-starvation class the token_budget check
                    # rejects, one layer up
                    raise ValueError(
                        f"request costs {req.cost} tokens > tenant "
                        f"{req.tenant!r} burst capacity {burst:g}: its "
                        "rate bucket could never afford it")
        except ValueError as e:
            req.state = REJECTED
            self.rejected += 1
            self._rejected_by_tenant[req.tenant] = \
                self._rejected_by_tenant.get(req.tenant, 0) + 1
            _M_REJECTED.inc()
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event("router.reject", rid=req.rid,
                                      tenant=req.tenant, reason=str(e))
            raise
        self._enqueue(req)
        if _journal.ACTIVE is not None:
            # reqtrace lifecycle edge: the routed request exists — the
            # anchor every later req.* event joins on (by rid)
            _journal.ACTIVE.event(
                "req.submit", rid=req.rid, at=req.arrival_t,
                tenant=req.tenant, trace=req.trace_id, cost=req.cost,
                prompt_tokens=len(req.prompt))
        return req

    def _enqueue(self, req):
        """Insert into the tenant queue keeping arrival order (a
        requeued request re-enters at its original arrival position)."""
        q = self._queues.setdefault(req.tenant, [])
        i = len(q)
        while i > 0 and q[i - 1].arrival_t > req.arrival_t:
            i -= 1
        q.insert(i, req)
        req.state = QUEUED
        _M_QUEUE.set(self.queue_depth)

    # -- the dispatch decision -----------------------------------------------
    def _eligible_tenants(self, now):
        out = []
        for tenant, q in self._queues.items():
            if not q:
                continue
            pol = self._policy(tenant)
            key = (pol.rate, pol.burst)
            cached = self._buckets.get(tenant)
            if cached is None or cached[0] != key:
                # rebuild on rate/burst CHANGE, not just first sight:
                # changing a live tenant's limits (new rate/burst,
                # unlimited <-> rated) must take effect, not serve a
                # stale bucket forever. Compared against a VALUE
                # snapshot taken at cache time — catching in-place
                # policy mutation as well as entry replacement — while
                # a config reloader rebuilding equal policies each
                # interval keeps the bucket level (no wiping the
                # tenant's accumulated rate debt)
                cached = (key, pol.bucket(now))
                self._buckets[tenant] = cached
            bucket = cached[1]
            if bucket is not None:
                # a queued request costlier than the bucket's capacity
                # can NEVER dispatch (the bucket caps at burst). The
                # submit-time burst guard only saw the policy of its
                # moment — a live rate-limit change (or a requeue into
                # a since-tightened tenant) can strand a head that
                # would gridlock the tenant forever: evict as rejected
                while q and q[0].cost > bucket.burst:
                    head = q.pop(0)
                    head.state = REJECTED
                    self.rejected += 1
                    self._rejected_by_tenant[tenant] = \
                        self._rejected_by_tenant.get(tenant, 0) + 1
                    _M_REJECTED.inc()
                    if _journal.ACTIVE is not None:
                        _journal.ACTIVE.event(
                            "router.reject", rid=head.rid,
                            tenant=tenant,
                            reason=f"cost {head.cost} > tenant burst "
                                   f"{bucket.burst:g} (policy changed "
                                   "after queue)")
                if not q:
                    continue
                head = q[0]
                if not bucket.peek(head.cost, now):
                    # tenant-bucket wait starts (once per queueing
                    # episode): the head is dispatchable but its
                    # tenant's rate bucket cannot yet afford it
                    if head.rate_hold_t is None:
                        head.rate_hold_t = now
                        self._rate_holds_by_tenant[tenant] = \
                            self._rate_holds_by_tenant.get(tenant, 0) + 1
                        if _journal.ACTIVE is not None:
                            _journal.ACTIVE.event(
                                "req.rate_hold", rid=head.rid, at=now,
                                tenant=tenant)
                    continue
                if head.rate_hold_t is not None:
                    # the bucket refilled: the hold closes HERE — time
                    # past this point (e.g. waiting for a replica slot)
                    # is router-queue wait, not rate-limit wait
                    head.rate_wait += now - head.rate_hold_t
                    head.rate_hold_t = None
            deficit = self._served.get(tenant, 0.0) / pol.weight
            out.append((deficit, tenant))
        return sorted(out)

    def _pick_replica(self, cost):
        """Accepting replica with the least outstanding tokens (and
        room under ``max_outstanding_per_replica``); lowest id on a
        tie — THE determinism rule the dispatch-trace tests pin."""
        best = None
        for rep in self.pool.active():
            if self.max_outstanding is not None and \
                    rep.outstanding_tokens + cost > self.max_outstanding:
                continue
            key = (rep.outstanding_tokens, rep.replica_id)
            if best is None or key < best[0]:
                best = (key, rep)
        return best[1] if best else None

    def dispatch(self, now=None):
        """Dispatch as many queued requests as policy allows; returns
        the ``(rid, replica_id)`` pairs dispatched, in order."""
        now = self.clock() if now is None else now
        out = []
        while True:
            cands = self._eligible_tenants(now)
            placed = False
            for _, tenant in cands:
                head = self._queues[tenant][0]
                rep = self._pick_replica(head.cost)
                if rep is None:
                    # no replica can take this head; a LARGER head
                    # elsewhere can't fit either, but a smaller one
                    # might — keep scanning tenants in deficit order
                    # (within a tenant, arrival order stays strict)
                    continue
                self._queues[tenant].pop(0)
                cached = self._buckets.get(tenant)
                bucket = cached[1] if cached else None
                if bucket is not None:
                    bucket.take(head.cost, now)
                self._served[tenant] = \
                    self._served.get(tenant, 0.0) + head.cost
                self._dispatch_one(head, rep, now)
                out.append((head.rid, rep.replica_id))
                placed = True
                break
            if not placed:
                break
        _M_QUEUE.set(self.queue_depth)
        return out

    def _dispatch_one(self, req, rep, now):
        req.state = DISPATCHED
        req.replica_id = rep.replica_id
        if req.admit_t is None:   # a requeue keeps the ORIGINAL admit
            req.admit_t = now
        if req.rate_hold_t is not None:   # belt-and-braces close
            req.rate_wait += now - req.rate_hold_t
            req.rate_hold_t = None
        req.dispatches.append((now, rep.replica_id))
        self._inflight[req.rid] = req
        self.dispatched += 1
        _M_DISPATCHED.inc()
        self.trace.append({"t": now, "rid": req.rid,
                           "replica": rep.replica_id,
                           "tenant": req.tenant})
        if _journal.ACTIVE is not None:
            # reqtrace lifecycle edge: dispatch segment N starts on
            # this replica's lane; rate_wait_ms is CUMULATIVE across
            # the request's queueing episodes (assembly reads the last)
            _journal.ACTIVE.event(
                "req.dispatch", rid=req.rid, at=now,
                replica=rep.replica_id, seq=len(req.dispatches),
                rate_wait_ms=req.rate_wait * 1e3, trace=req.trace_id)
        rep.submit(req)

    # -- completion + failure ------------------------------------------------
    def poll(self, now=None):
        """Collect finished requests from every replica; returns the
        newly completed ``FleetRequest``s. Also retires replicas whose
        scale-down drain just emptied."""
        done = []
        for rep in list(self.pool.replicas):
            for res in rep.poll():
                req = self._inflight.pop(res["rid"], None)
                if req is None:
                    continue  # cancelled/unknown: replica-side record
                req.state = res.get("state", FINISHED)
                req.tokens = list(res.get("tokens") or [])
                req.first_token_t = res.get("first_token_t")
                req.finish_t = res.get("finish_t")
                req.preemptions += int(res.get("preemptions") or 0)
                self.completed.append(req)
                _M_COMPLETED.inc()
                done.append(req)
            if rep.draining and rep.inflight_count == 0 and \
                    rep.state not in ("DEAD", "RETIRED"):
                self.pool.retire(rep)
                if _journal.ACTIVE is not None:
                    _journal.ACTIVE.event("router.scale",
                                          direction="down_complete",
                                          replica=rep.replica_id)
        _M_REPLICAS.set(len(self.pool.active()))
        return done

    def check_replicas(self, now=None):
        """Health-sweep the pool: a dead (or watchdog-killed hung)
        replica's in-flight requests requeue by original arrival, then
        the pool relaunches it warm (``ReplicaSupervisor`` budget +
        backoff) — unless it was a scale-down drain, which just
        retires. Returns ``[(replica_id, reason, n_requeued)]``."""
        now = self.clock() if now is None else now
        out = []
        for rep, reason in self.pool.check_health(now):
            stranded = [self._inflight.pop(r.rid)
                        for r in rep.take_inflight()
                        if r.rid in self._inflight]
            for req in sorted(stranded, key=lambda r: r.arrival_t):
                req.requeues += 1
                req.requeue_ts.append(now)
                self.requeued += 1
                self._requeued_by_tenant[req.tenant] = \
                    self._requeued_by_tenant.get(req.tenant, 0) + 1
                _M_REQUEUED.inc()
                self._enqueue(req)
                if _journal.ACTIVE is not None:
                    # reqtrace lifecycle edge: the dispatch segment on
                    # the dead replica ends here (per-rid twin of the
                    # aggregate router.requeue event below)
                    _journal.ACTIVE.event(
                        "req.requeue", rid=req.rid, at=now,
                        replica=rep.replica_id, reason=reason)
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event(
                    "router.requeue", replica=rep.replica_id,
                    reason=reason, rids=[r.rid for r in stranded])
            self.recent_events.append(
                {"t": now, "kind": "requeue",
                 "replica": rep.replica_id, "reason": reason,
                 "requeued": len(stranded)})
            if rep.draining:
                self.pool.retire(rep)
            else:
                self.pool.relaunch(rep)
            out.append((rep.replica_id, reason, len(stranded)))
        if out:
            _M_REPLICAS.set(len(self.pool.active()))
        return out

    # -- autoscaling ---------------------------------------------------------
    def exposition(self):
        """The fleet's live signal plane as ONE Prometheus exposition:
        router gauges + every replica's SLO gauges — same-process
        engines directly, worker processes scraped-and-merged from
        their per-replica exporters (``obs.export``'s multi-process
        path). This text IS what the autoscaler consumes."""
        from ...obs import export as _export

        texts = ["\n".join(_export.router_lines(self)) + "\n"]
        engines = self.pool.local_engines()
        texts.append("\n".join(
            _export.tenant_lines(router=self, engines=engines)) + "\n")
        if engines:
            texts.append(
                "\n".join(_export.slo_lines(engines=engines)) + "\n")
        for target in self.pool.scrape_targets():
            try:
                texts.append(_export.scrape(target))
            except Exception:
                continue  # a mid-restart replica just misses one tick
        return _export.merge_expositions(texts)

    def autoscale_tick(self, now=None, exposition=None):
        """One autoscaler observation over the live scrape: ``"up"``
        launches a warm replica, ``"down"`` DRAINS the least-loaded one
        (never kills mid-decode; ``poll`` retires it once empty).
        ``exposition`` lets ``step()`` hand in the text it already
        built for this tick (shared with the SLO evaluator) instead of
        paying a second scrape sweep."""
        if self.autoscaler is None:
            return None
        from .autoscale import Autoscaler

        now = self.clock() if now is None else now
        if exposition is None:
            exposition = self.exposition()
        signals = Autoscaler.signals_from_scrape(exposition)
        signals.setdefault("queue_depth", float(self.queue_depth))
        n = len(self.pool.active())
        # the pool's own max_replicas can sit BELOW the autoscaler's,
        # and its capacity counts STARTING/DRAINING replicas and
        # backoff-pending relaunches that n (accepting only) misses:
        # clamp INSIDE observe so a can't-scale tick is a clean hold —
        # no cooldown burned, no breach streak reset — instead of a
        # crash of the serve loop or a committed phantom "up"
        headroom = self.pool.headroom()
        decision = self.autoscaler.observe(
            signals, replicas=n, now=now,
            max_replicas=None if headroom is None else n + headroom)
        if decision == "up":
            rep = self.pool.scale_up(wait=False)
            self.scale_ups += 1
            _M_SCALE_UP.inc()
            _M_REPLICAS.set(len(self.pool.active()))
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event("router.scale", direction="up",
                                      replica=rep.replica_id,
                                      replicas=len(self.pool.active()))
            self.recent_events.append(
                {"t": now, "kind": "scale_up",
                 "replica": rep.replica_id})
        elif decision == "down":
            active = self.pool.active()
            if len(active) > 1:
                rep = min(active, key=lambda r: (r.outstanding_tokens,
                                                 r.replica_id))
                rep.drain()
                self.scale_downs += 1
                _M_SCALE_DOWN.inc()
                if _journal.ACTIVE is not None:
                    _journal.ACTIVE.event(
                        "router.scale", direction="down",
                        replica=rep.replica_id,
                        replicas=len(self.pool.active()))
                self.recent_events.append(
                    {"t": now, "kind": "scale_down",
                     "replica": rep.replica_id})
            else:
                decision = None  # never drain the last replica
        return decision

    # -- driving loops -------------------------------------------------------
    def step(self, now=None):
        """One router iteration: health sweep (requeue + relaunch),
        dispatch, pump in-process replicas one engine step, collect
        completions. Returns the newly completed requests."""
        now = self.clock() if now is None else now
        self.check_replicas(now)
        self.dispatch(now)
        self.pool.pump()
        done = self.poll(now)
        if (self.autoscaler is not None or self.slo is not None) \
                and (self._next_autoscale_t is None
                     or now >= self._next_autoscale_t):
            self._next_autoscale_t = now + self.autoscale_interval_s
            # ONE exposition per throttled tick, shared by the
            # autoscaler and the SLO evaluator: attaching SLO
            # monitoring must not change the scrape budget
            text = self.exposition()
            if self.autoscaler is not None:
                self.autoscale_tick(now, exposition=text)
            if self.slo is not None:
                # fairness rides the same throttled tick: tenant_hog
                # sees measured-vs-weight shares next to the latency
                # signals, at zero extra scrape cost
                from ...obs import usage as _usage
                self.slo.observe(text=text, now=now,
                                 extra=_usage.fairness_record(self))
        return done

    def run_until_drained(self, timeout_s=120.0, sleep_s=0.0):
        """Drive ``step()`` until every submitted request reached a
        terminal state (or ``timeout_s`` of wall time passed — the
        loop bound for process pools whose work happens elsewhere).
        Returns the number of requests completed."""
        deadline = time.monotonic() + float(timeout_s)
        n0 = len(self.completed)
        while self._inflight or self.queue_depth:
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"router did not drain in {timeout_s}s: "
                    f"{len(self._inflight)} in flight, "
                    f"{self.queue_depth} queued")
            if sleep_s and (self._inflight or self.queue_depth):
                time.sleep(sleep_s)
        return len(self.completed) - n0

    # -- introspection -------------------------------------------------------
    @property
    def queue_depth(self):
        return sum(len(q) for q in self._queues.values())

    @property
    def inflight(self):
        return len(self._inflight)

    def stats(self):
        """Router truth (plain data): dispatch/requeue/reject counts,
        per-replica outstanding, per-tenant token shares, scale events,
        and exact latency percentiles over completed requests — the
        numbers ``obs.export.router_lines`` must reproduce bitwise."""
        from ...obs.metrics import exact_percentile

        served_total = sum(self._served.values())
        out = {
            "queue_depth": self.queue_depth,
            "inflight": len(self._inflight),
            "dispatched": self.dispatched,
            "requeued": self.requeued,
            "rejected": self.rejected,
            "completed": len(self.completed),
            "replicas": len(self.pool.active()),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "per_replica": {
                rep.replica_id: {
                    "state": rep.state,
                    "outstanding_tokens": rep.outstanding_tokens,
                    "inflight": rep.inflight_count,
                }
                for rep in self.pool.replicas
            },
            "tenants": {
                t: {"served_tokens": served,
                    "share": (served / served_total) if served_total
                    else 0.0,
                    "weight": self._policy(t).weight,
                    "queued": len(self._queues.get(t) or [])}
                for t, served in sorted(self._served.items())
            },
        }
        fin = [r for r in self.completed if r.state == FINISHED]
        lat = {
            "ttft_ms": [(r.first_token_t - r.arrival_t) * 1e3
                        for r in fin if r.first_token_t is not None
                        and r.arrival_t is not None],
            "e2e_ms": [(r.finish_t - r.arrival_t) * 1e3 for r in fin
                       if r.finish_t is not None
                       and r.arrival_t is not None],
            "tpot_ms": [(r.finish_t - r.first_token_t) * 1e3 /
                        (len(r.tokens) - 1) for r in fin
                        if len(r.tokens) > 1
                        and r.first_token_t is not None
                        and r.finish_t is not None],
        }
        for name, xs in lat.items():
            if xs:
                out[name] = {"count": len(xs),
                             "p50": exact_percentile(xs, 50),
                             "p99": exact_percentile(xs, 99)}
        return out

    def journal_summary(self):
        """One ``router.summary`` event with the final truth (the
        record ``run_report``/``fleet_report`` render); last wins."""
        if _journal.ACTIVE is None:
            return
        st = self.stats()
        _journal.ACTIVE.event(
            "router.summary", dispatched=st["dispatched"],
            requeued=st["requeued"], rejected=st["rejected"],
            completed=st["completed"], replicas=st["replicas"],
            scale_ups=st["scale_ups"], scale_downs=st["scale_downs"],
            tenants={t: round(v["share"], 6)
                     for t, v in st["tenants"].items()},
            ttft_p99_ms=(st.get("ttft_ms") or {}).get("p99"))
        # full per-tenant rollup (weights, shares, outcomes, latency
        # percentiles) for the chargeback/fairness readers — a second
        # event so router.summary's shape (and every report pinned to
        # it) stays byte-compatible
        from ...obs import usage as _usage

        tu = _usage.router_tenant_usage(self)
        _journal.ACTIVE.event("tenant.summary",
                              served_total=tu["served_total"],
                              tenants=tu["tenants"])

    def close(self):
        """Journal the summary and shut the pool down (drain-free stop:
        callers wanting a graceful end drain first)."""
        self.journal_summary()
        if self.slo is not None:
            self.slo.journal_summary()
        self.pool.shutdown()
