"""SLO-aware autoscaling: queue-depth + TTFT/TPOT signals -> replica
count decisions.

The decision layer between the PR-13 signal plane and the pool: the
:class:`Autoscaler` consumes the SAME Prometheus exposition a human's
dashboard scrapes (``obs.export`` — router gauges, per-replica
``serving_slo_*``), via :meth:`Autoscaler.signals_from_scrape`, and
answers one question per observation: scale up, scale down, or hold.

Discipline (each rule pinned by tests/test_serve_fleet.py on synthetic
SLO series under a ManualClock):

- **Hysteresis, not hair-trigger.** A breach (router queue depth over
  ``queue_high``, or TTFT/TPOT p99 over its SLO) must persist for
  ``breach_patience`` CONSECUTIVE observations before a scale-up; a
  quiet fleet (queue at/below ``queue_low``, SLOs met) must persist
  for ``low_patience`` observations before a scale-down. One noisy
  scrape never moves the fleet.
- **Cooldown.** After any decision, ``cooldown_s`` of clock time must
  pass before the next — scale-up takes effect only after the new
  replica warms, and reacting to the pre-warm signal again would
  double-scale.
- **Bounds.** Never below ``min_replicas`` or above ``max_replicas``.
- **Scale-down drains.** The autoscaler only *decides*; the router
  picks the least-loaded replica and ``drain()``s it — in-flight
  decodes finish where they are, the replica retires empty. Nothing is
  killed mid-decode for capacity reasons.
"""
from __future__ import annotations

import re
import time

__all__ = ["Autoscaler", "per_replica_slo_from_scrape"]

# exposition keys (obs.export naming): one place, shared with the
# signal parser's regexes below
_QUEUE_KEY = "paddle_tpu_fleet_router_queue_depth"
_SLO_RE = re.compile(
    r"^paddle_tpu_serving_slo_(ttft|tpot)_ms\{"
    r"(?=[^}]*\breplica=\"(?P<rep>[^\"]*)\")"
    r"(?=[^}]*\bq=\"(?P<q>p\d+)\")[^}]*\}$")
_RUNNING_RE = re.compile(
    r"^paddle_tpu_serving_slo_running\{[^}]*\breplica=\"([^\"]*)\"")
_ENGINE_QUEUE_RE = re.compile(
    r"^paddle_tpu_serving_slo_queue_depth\{")


def per_replica_slo_from_scrape(text):
    """Per-replica SLO latencies from the same exposition
    :meth:`Autoscaler.signals_from_scrape` reads, UNpooled:
    ``{replica: {"ttft_p99_ms": v, "tpot_p50_ms": v, ...}}``. The
    attribution complement of the autoscaler's worst-of signal — the
    SLO evaluator's worst-offender lookup and the /statusz per-replica
    table both read this."""
    from ...obs.export import parse_prometheus_text

    vals = text if isinstance(text, dict) \
        else parse_prometheus_text(text)
    out = {}
    for key, v in vals.items():
        m = _SLO_RE.match(key)
        if not m:
            continue
        rep = m.group("rep")
        out.setdefault(rep, {})[
            f"{m.group(1)}_{m.group('q')}_ms"] = v
    return out


class Autoscaler:
    """Deterministic scale decisions over scraped SLO signals."""

    def __init__(self, min_replicas=1, max_replicas=4, *,
                 queue_high=8.0, queue_low=1.0, ttft_p99_slo_ms=None,
                 tpot_p99_slo_ms=None, breach_patience=2,
                 low_patience=4, cooldown_s=30.0, clock=None):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        if self.min_replicas < 1 or \
                self.max_replicas < self.min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.ttft_p99_slo_ms = ttft_p99_slo_ms
        self.tpot_p99_slo_ms = tpot_p99_slo_ms
        self.breach_patience = int(breach_patience)
        self.low_patience = int(low_patience)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else time.monotonic
        self._breaches = 0
        self._lows = 0
        self._last_decision_t = None
        self.decisions = []   # [(t, "up"/"down", reason)] — the trace

    # -- signal extraction ---------------------------------------------------
    @staticmethod
    def signals_from_scrape(text):
        """Autoscaler inputs from a Prometheus exposition (text or an
        already-parsed ``parse_prometheus_text`` dict): router queue
        depth (falling back to the per-replica engine queue gauges
        summed), worst per-replica TTFT/TPOT p99, and the replica count
        visible in the scrape."""
        from ...obs.export import parse_prometheus_text

        vals = text if isinstance(text, dict) \
            else parse_prometheus_text(text)
        sig = {}
        replicas = set()
        engine_queue = 0.0
        saw_engine_queue = False
        for key, v in vals.items():
            if key == _QUEUE_KEY:
                sig["queue_depth"] = v
                continue
            m = _SLO_RE.match(key)
            if m and m.group("q") == "p99":
                k = f"{m.group(1)}_p99_ms"
                sig[k] = max(sig.get(k, 0.0), v)
                continue
            m = _RUNNING_RE.match(key)
            if m:
                replicas.add(m.group(1))
                continue
            if _ENGINE_QUEUE_RE.match(key):
                engine_queue += v
                saw_engine_queue = True
        if "queue_depth" not in sig and saw_engine_queue:
            sig["queue_depth"] = engine_queue
        if replicas:
            sig["replicas"] = len(replicas)
        return sig

    # -- the decision --------------------------------------------------------
    def _breached(self, sig):
        if sig.get("queue_depth", 0.0) > self.queue_high:
            return f"queue_depth {sig['queue_depth']:g} > " \
                   f"{self.queue_high:g}"
        for name, slo in (("ttft", self.ttft_p99_slo_ms),
                          ("tpot", self.tpot_p99_slo_ms)):
            if slo is None:
                continue
            v = sig.get(f"{name}_p99_ms")
            if v is not None and v > float(slo):
                return f"{name}_p99 {v:g}ms > SLO {float(slo):g}ms"
        return None

    def _low(self, sig):
        if sig.get("queue_depth", 0.0) > self.queue_low:
            return False
        for name, slo in (("ttft", self.ttft_p99_slo_ms),
                          ("tpot", self.tpot_p99_slo_ms)):
            if slo is None:
                continue
            v = sig.get(f"{name}_p99_ms")
            if v is not None and v > float(slo):
                return False
        return True

    def observe(self, signals, replicas=None, now=None,
                max_replicas=None):
        """One observation -> ``"up"`` / ``"down"`` / ``None``.
        ``signals`` is a :meth:`signals_from_scrape` dict (or any dict
        with ``queue_depth`` / ``*_p99_ms``); ``replicas`` overrides
        the scrape-visible replica count with pool truth.
        ``max_replicas`` tightens the up-bound for THIS observation
        (the router passes the pool's remaining capacity, which counts
        STARTING/DRAINING replicas and backoff-pending relaunches the
        active count misses) — clamping inside the decision keeps a
        can't-scale observation from committing an "up": no cooldown
        burned, no breach streak reset, no phantom decisions entry."""
        now = self.clock() if now is None else now
        n = int(replicas if replicas is not None
                else signals.get("replicas", self.min_replicas))
        cap = self.max_replicas if max_replicas is None \
            else min(self.max_replicas, int(max_replicas))
        breach = self._breached(signals)
        if breach:
            self._breaches += 1
            self._lows = 0
        elif self._low(signals):
            self._lows += 1
            self._breaches = 0
        else:
            self._breaches = 0
            self._lows = 0
        if self._last_decision_t is not None and \
                now - self._last_decision_t < self.cooldown_s:
            return None
        if breach and self._breaches >= self.breach_patience and \
                n < cap:
            self._breaches = 0
            self._lows = 0
            self._last_decision_t = now
            self.decisions.append((now, "up", breach))
            return "up"
        if self._lows >= self.low_patience and n > self.min_replicas:
            self._lows = 0
            self._last_decision_t = now
            self.decisions.append((now, "down", "idle"))
            return "down"
        return None
