"""Elastic-serving chaos drill: kill a replica mid-decode, lose nothing.

The fleet's acceptance drill (the serving twin of
``tools/elastic_run.py``'s gang drill): N=2 ``worker`` processes behind
a :class:`~.router.Router`, sharing one AOT executable cache and one
fleet journal root. The ``replica_kill`` injector hard-kills replica 1
inside serve step ``KILL_STEP`` (``os._exit`` — no flush, no goodbye:
machine loss). The drill then proves, end to end:

1. **No request is lost.** Every submitted request reaches FINISHED —
   the victims requeue through the router and finish elsewhere (or on
   the relaunched replica).
2. **Token-for-token oracle identity.** Every request's output equals
   the single-engine dense oracle (``TinyLM.reference_generate``) —
   re-dispatch re-prefills the original prompt and greedy decode is
   deterministic, so a kill is invisible in the tokens.
3. **Requeue keeps arrival order.** The stranded requests re-dispatch
   in their ORIGINAL arrival order (the router-level mirror of the
   scheduler's preemption rule).
4. **Relaunch is AOT-warm.** The relaunched incarnation's journal
   segment records ZERO ``via=="xla"`` compile events and at least one
   ``via=="aot_disk"`` hydration — scale-up/recovery pays deserialize,
   never XLA (PR 12's promise, under fire).

The run is cached once per process (``drill_result``) and shared by
``tools/chaos_run.py``'s ``replica_kill`` scenario and
``tests/test_serve_fleet.py`` — tier-1 pays for ONE drill.
"""
from __future__ import annotations

import json
import os
import tempfile
import time

__all__ = ["run_drill", "drill_result", "KILL_STEP"]

KILL_STEP = 4      # serve step the victim dies in (mid-decode)
VICTIM = 1
N_REQUESTS = 6     # split ~3/3; max_new=5 means nothing finishes
MAX_NEW = 5        # before the step-4 kill — every strand is mid-decode

_RESULT = None


def _requests(vocab, n=N_REQUESTS, seed=7):
    import numpy as np

    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        plen = int(rng.randint(4, 7))
        out.append(([int(x) for x in rng.randint(0, vocab, plen)],
                    MAX_NEW))
    return out


def _relaunch_compiles(rank_dir):
    """Compile-event provenance of the LAST incarnation in a rank
    journal (relaunches append to the same file; segments split on
    ``run_start``)."""
    path = os.path.join(rank_dir, "journal.jsonl")
    segments = [[]]
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from the os._exit kill
            if rec.get("t") == "run_start":
                segments.append([])
            segments[-1].append(rec)
    last = segments[-1]
    via = {"xla": 0, "aot_disk": 0, "none": 0}
    for rec in last:
        if rec.get("t") == "event" and rec.get("kind") == "compile":
            via[rec.get("via") or "none"] = \
                via.get(rec.get("via") or "none", 0) + 1
    # one segment per run_start (segments[0] is the pre-header void)
    return via, len(segments) - 1


def _scan_lockdep_cycles(run_dir):
    """Every ``lockdep.cycle`` event journaled under ``run_dir`` (any
    rank / the router journal) — the worker-side PTC004 witness."""
    cycles = []
    for dirpath, _dirnames, filenames in os.walk(run_dir):
        for fn in filenames:
            if not fn.endswith(".jsonl"):
                continue
            with open(os.path.join(dirpath, fn),
                      encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail from the kill
                    if rec.get("t") == "event" and \
                            rec.get("kind") == "lockdep.cycle":
                        cycles.append(rec.get("cycle"))
    return cycles


def run_drill(root=None, keep=False):
    """Run the 2-replica kill drill; returns the result dict (with a
    ``failures`` list — empty on success)."""
    from ..engine import TinyLM
    from ...obs import journal as _journal
    from .pool import ReplicaPool, ReplicaSpec
    from .router import Router

    from ...obs import lockdep as _lockdep

    failures = []
    own_root = root is None
    root = root or tempfile.mkdtemp(prefix="pt_fleet_drill_")
    run_dir = os.path.join(root, "run")
    spec = ReplicaSpec(
        vocab_size=32, num_heads=2, head_dim=8, seed=0,
        pages=16, page_size=4, max_seq_len=16, token_budget=64,
        # warm bound 4: the requeue routes the ≤3 stranded requests to
        # the EMPTY relaunched replica, so no decode batch exceeds 4
        # lanes anywhere — warming buckets past that would only slow
        # the one cold (compiling) incarnation
        max_batch=4, warm=True,
        aot_cache_dir=os.path.join(root, "aot"),
        run_dir=run_dir, metrics_port=0,
        env={"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
             # quiet the journal's background analysis compiles: CPU
             # contention inside workers racing the drill's wall clock,
             # and an analysis compile must never muddy the zero-xla
             # assertion's compile-event stream
             "PADDLE_TPU_JOURNAL_FLOPS": "0",
             "PADDLE_TPU_TRACE": "",
             "PADDLE_TPU_CHAOS": "",
             # every worker runs the lockdep runtime in raise mode: a
             # lock-order cycle anywhere in the serve loop crashes the
             # replica, strands its requests, and fails the drill —
             # the acceptance gate for the PTC004 class
             "PADDLE_TPU_LOCKDEP": "1"},
        env_for_replica=lambda rid, attempt: (
            {"PADDLE_TPU_CHAOS":
             f"replica_kill:at={KILL_STEP},rank={VICTIM}"}
            if rid == VICTIM and attempt == 0 else {}),
        hang_timeout_s=120.0, startup_timeout_s=300.0)

    model = TinyLM(vocab_size=32, num_heads=2, head_dim=8, seed=0)
    trace = _requests(spec.vocab_size)
    oracle = [model.reference_generate(p, m) for p, m in trace]

    from ...resilience.elastic import ReplicaSupervisor

    # lockdep on the PARENT side too (scoped): the router journal's and
    # each ProcessReplica's locks are constructed below, so they come
    # out instrumented; the router thread's consume path and the reader
    # threads' produce path both feed the order graph. Raise mode — a
    # cycle aborts the drill into `failures`.
    prev_lockdep = _lockdep.mode()
    _lockdep.enable(_lockdep.MODE_RAISE)
    lockdep_before = len(_lockdep.violations())

    prev_active = _journal.ACTIVE
    router_journal = _journal.RunJournal(
        os.path.join(run_dir, _journal.ROUTER_DIR), rank=None,
        flush_every=1, compute_flops=False)
    router_journal.start()
    _journal.ACTIVE = router_journal
    pool = None
    router = None
    try:
        pool = ReplicaPool(
            spec, replicas=2, mode="process",
            supervisor=ReplicaSupervisor(max_restarts=2,
                                         backoff_s=0.05, jitter=0.0))
        from .router import TenantPolicy

        # every submission rides tenant "drill": the chargeback plane
        # (obs.usage) gets exercised under replica loss, and the gauge
        # check below proves the scraped tenant_* series equal router
        # truth bitwise on this live 2-replica run
        router = Router(pool,
                        tenants={"drill": TenantPolicy(weight=1.0)})
        t0 = time.time()
        reqs = [router.submit(p, max_new_tokens=m,
                              arrival_t=t0 + i * 1e-3,
                              tenant="drill")
                for i, (p, m) in enumerate(trace)]
        router.run_until_drained(timeout_s=300.0, sleep_s=0.02)
        # the victim's relaunch is deferred behind its supervisor
        # backoff (never slept on the router thread): the survivor can
        # finish every stranded request before the spawn fires, so
        # drive the health sweep until it does — the journal
        # assertions below read the relaunched incarnation's warm
        spawn_deadline = time.time() + 60.0
        while pool._pending and time.time() < spawn_deadline:
            router.check_replicas()
            time.sleep(0.01)
        stats = router.stats()
        dispatch_trace = list(router.trace)
        # tenant chargeback gauges, live: scrape the router's
        # tenant_* exposition and parse it back — every series must
        # equal the obs.usage rollup BITWISE (repr round-trip), on a
        # fleet that just survived a replica kill with requeues
        from ...obs import export as _export
        from ...obs import usage as _usage

        tenant_usage = _usage.router_tenant_usage(router)
        scraped = _export.parse_prometheus_text(
            "\n".join(_export.tenant_lines(router=router)))
        for tenant, d in tenant_usage["tenants"].items():
            for key in ("weight_share", "served_tokens", "share",
                        "requests", "completed", "requeued",
                        "preemptions", "prompt_tokens",
                        "decode_tokens"):
                skey = (f'paddle_tpu_tenant_{key}'
                        f'{{tenant="{tenant}"}}')
                if scraped.get(skey) != float(d.get(key, 0)):
                    failures.append(
                        f"tenant gauge {skey}={scraped.get(skey)} != "
                        f"router truth {d.get(key, 0)} (bitwise gate)")
        if not tenant_usage["served_total"]:
            failures.append(
                "tenant metering saw zero served tokens — the drill's "
                "tenant='drill' stamps went missing")
        # graceful stop BEFORE the journal assertions: the live
        # workers' buffered tails flush on their way out
        router.close()
        router = None

        # 1. nothing lost
        for r in reqs:
            if r.state != "FINISHED":
                failures.append(f"{r.rid} ended {r.state}, not FINISHED")
        # 2. oracle identity
        for r, ref in zip(reqs, oracle):
            if r.tokens != ref:
                failures.append(
                    f"{r.rid} tokens {r.tokens} != oracle {ref} "
                    f"(requeues={r.requeues})")
        # the kill actually stranded someone (else the drill is vacuous)
        requeued = [r for r in reqs if r.requeues]
        if stats["requeued"] < 1 or not requeued:
            failures.append(
                f"kill at step {KILL_STEP} stranded no request "
                f"(requeued={stats['requeued']}) — drill vacuous")
        # 3. requeued re-dispatches follow original arrival order
        requeued_rids = {r.rid for r in requeued}
        redis = [e["rid"] for e in dispatch_trace
                 if e["rid"] in requeued_rids][len(requeued_rids):]
        arrival_order = [r.rid for r in
                         sorted(requeued, key=lambda r: r.arrival_t)]
        if redis != arrival_order:
            failures.append(
                f"requeued dispatch order {redis} != arrival order "
                f"{arrival_order}")
        # 4. the relaunched incarnation is AOT-warm: zero xla compiles
        rank_dir = os.path.join(run_dir,
                                _journal.rank_subdir(VICTIM))
        via, incarnations = _relaunch_compiles(rank_dir)
        if incarnations < 2:
            failures.append(
                f"victim journal shows {incarnations} "
                "incarnation(s) — was it relaunched at all?")
        if via["xla"] != 0:
            failures.append(
                f"relaunched replica journaled {via['xla']} "
                f"via=='xla' compile(s) — scale-up paid XLA: {via}")
        if via["aot_disk"] < 2:
            failures.append(
                f"relaunched replica hydrated only "
                f"{via['aot_disk']} entries from the shared AOT "
                "cache (warm() covers prefill+decode buckets)")
        # 5. zero lock-order cycles, parent AND workers: parent-side
        # from the live graph, worker-side from journaled
        # lockdep.cycle events (a worker in raise mode also crashes,
        # which assertions 1-2 already catch — this names the cause)
        parent_cycles = _lockdep.violations()[lockdep_before:]
        worker_cycles = _scan_lockdep_cycles(run_dir)
        if parent_cycles:
            failures.append(
                f"lockdep: {len(parent_cycles)} PTC004 cycle(s) on "
                f"the router side: "
                f"{[v['cycle'] for v in parent_cycles]}")
        if worker_cycles:
            failures.append(
                f"lockdep: {len(worker_cycles)} PTC004 cycle(s) "
                f"journaled by workers: {worker_cycles}")
        # 6. request timelines (obs.reqtrace): every requeued request's
        # assembled timeline spans BOTH replica incarnations — the
        # victim's dispatch segment AND the re-dispatched one's — and
        # the merged Perfetto export carries the cross-pid flow arrow.
        # Workers run with span tracing OFF, so the request lanes are
        # journal-derived by construction (zero trace-file sources).
        from ...obs import fleet as obs_fleet
        from ...obs import reqtrace as _reqtrace

        timelines = _reqtrace.assemble_run(run_dir)
        attribution = {a["rid"]: a
                       for a in _reqtrace.attribute_run(timelines)}
        for rid in sorted(requeued_rids):
            segs = (timelines.get(rid) or {}).get("segments") or []
            seg_reps = {s["replica"] for s in segs}
            if len(segs) < 2 or len(seg_reps) < 2:
                failures.append(
                    f"reqtrace: {rid} requeued but its timeline has "
                    f"{len(segs)} segment(s) on replicas "
                    f"{sorted(seg_reps)} — expected the victim's AND "
                    "the re-dispatched replica's")
            att = attribution.get(rid)
            if att is None or not att["requeue_ms"] > 0:
                failures.append(
                    f"reqtrace: {rid} requeued but its attribution "
                    f"shows no requeue loss: {att}")
        merged = obs_fleet.merge_chrome_traces(
            run_dir, os.path.join(root, "merged_trace.json"))
        with open(merged["path"], encoding="utf-8") as f:
            merged_events = json.load(f).get("traceEvents") or []
        flow_pairs = {}
        for ev in merged_events:
            if ev.get("ph") in ("s", "f"):
                flow_pairs.setdefault(ev.get("id"), {})[ev["ph"]] = ev
        cross_flows = [fl for fl in flow_pairs.values()
                       if "s" in fl and "f" in fl
                       and fl["s"].get("pid") != fl["f"].get("pid")]
        cross_flow_rids = sorted(
            {(fl["s"].get("args") or {}).get("rid")
             for fl in cross_flows})
        if requeued_rids and not cross_flows:
            failures.append(
                "reqtrace: merged trace carries no cross-pid flow "
                "event — a requeued request should visibly cross "
                "from the victim's lane to the re-dispatched one's")
        result = {
            "failures": failures, "run_dir": run_dir, "root": root,
            "stats": stats, "trace": dispatch_trace,
            "requeued_rids": sorted(requeued_rids),
            "relaunch_via": via, "incarnations": incarnations,
            "oracle": oracle,
            "requests": [{"rid": r.rid, "state": r.state,
                          "tokens": r.tokens, "requeues": r.requeues,
                          "arrival_t": r.arrival_t,
                          "admit_t": r.admit_t} for r in reqs],
            "lockdep": {"mode": "raise",
                        "parent_cycles": parent_cycles,
                        "worker_cycles": worker_cycles},
            "request_timelines": {rid: tl["segments"]
                                  for rid, tl in timelines.items()},
            "request_attribution": attribution,
            "merged_trace": merged,
            "cross_flow_rids": cross_flow_rids,
            "tenant_usage": tenant_usage,
        }
    except Exception as e:  # a harness crash is a drill failure too
        failures.append(f"drill harness raised {type(e).__name__}: {e}")
        result = {"failures": failures, "run_dir": run_dir,
                  "root": root, "stats": None, "trace": [],
                  "requeued_rids": [], "relaunch_via": None,
                  "incarnations": 0, "oracle": oracle, "requests": [],
                  "lockdep": {"mode": "raise",
                              "parent_cycles":
                              _lockdep.violations()[lockdep_before:],
                              "worker_cycles": []},
                  "request_timelines": {}, "request_attribution": {},
                  "merged_trace": None, "cross_flow_rids": [],
                  "tenant_usage": None}
    finally:
        if prev_lockdep is not None:
            _lockdep.enable(prev_lockdep)
        else:
            _lockdep.disable()
        try:
            if router is not None:
                router.close()
            elif pool is not None:
                pool.shutdown()
        except Exception:
            pass
        try:
            router_journal.close()
        except Exception:
            pass
        if _journal.ACTIVE is None and prev_active is not None \
                and not prev_active.closed:
            _journal.ACTIVE = prev_active
    if own_root and not keep and not failures:
        import atexit
        import shutil

        # keep a FAILED drill's artifacts for the postmortem; clean
        # successful ones at exit (fleet_report's self-test still reads
        # the journals until then)
        atexit.register(shutil.rmtree, root, ignore_errors=True)
    return result


def drill_result(refresh=False):
    """The process-cached drill run — chaos_run, fleet_report and the
    pytest suite all read ONE execution."""
    global _RESULT
    if _RESULT is None or refresh:
        _RESULT = run_drill()
    return _RESULT
