"""paddle_tpu.serving.fleet: multi-replica serve router, SLO-aware
autoscaling, elastic replica supervision.

The millions-of-users topology (ROADMAP item 5) composed from five
existing subsystems: N continuous-batching ``ServeEngine`` replicas
(PR 7) behind a load-aware :class:`~.router.Router` with per-tenant
fairness + rate limits, replica processes heartbeat-watched and
relaunched in the PR-8 gang style (``resilience.ReplicaSupervisor``),
hydrating from a SHARED AOT executable cache (PR 12) so scale-up pays
deserialize instead of XLA, journaling per-rank and exporting live SLO
gauges through the PR-13 signal plane, and an :class:`~.autoscale
.Autoscaler` consuming that scrape to drive scale-up / drain-based
scale-down.

- ``router.Router`` — least-outstanding-tokens dispatch, tenant
  fairness/rate limits, arrival-order requeue on replica death;
  deterministic under an injectable clock.
- ``pool.ReplicaPool`` / ``ReplicaSpec`` — in-process or worker-process
  replicas with heartbeats, per-rank journals, per-replica ``/metrics``.
- ``autoscale.Autoscaler`` — hysteresis + cooldown over queue-depth and
  TTFT/TPOT p99 signals in the Prometheus scrape format.
- ``worker`` — the replica process entry
  (``python -m paddle_tpu.serving.fleet.worker``).
- ``drill`` — the kill-a-replica-mid-decode acceptance drill
  (``tools/chaos_run.py replica_kill``).

``tools/serve_bench.py --replicas N`` drives a Poisson trace through
an in-process fleet and gates aggregate p50/p99 TTFT/TPOT.
"""
from .autoscale import Autoscaler
from .pool import LocalReplica, ProcessReplica, ReplicaPool, ReplicaSpec
from .router import FleetRequest, Router, TenantPolicy, TokenBucket

__all__ = [
    "Router", "FleetRequest", "TenantPolicy", "TokenBucket",
    "ReplicaPool", "ReplicaSpec", "LocalReplica", "ProcessReplica",
    "Autoscaler",
]
