"""Serve-replica worker process: ``python -m paddle_tpu.serving.fleet.worker``.

One replica of a :class:`~.pool.ReplicaPool` in ``mode="process"``: a
``ServeEngine`` behind a newline-JSON stdin/stdout protocol, composed
from the PR stack the fleet exists to tie together —

- **AOT-warm start** (PR 12): with ``PADDLE_TPU_AOT_CACHE`` pointing
  at the pool's shared cache, ``--warm`` compiles-or-hydrates every
  prefill/decode bucket BEFORE the ``ready`` line, so a relaunched or
  scaled-up replica answers its first request with zero XLA compiles
  (the drill reads the journal to prove it).
- **Per-rank journal** (PR 13): ``PADDLE_TPU_RUN_DIR`` auto-starts the
  flight recorder in this replica's ``rank_NN`` subdir; request
  records + compile events land there for ``tools/fleet_report.py``.
- **Heartbeat** (PR 8): beats from the SERVE LOOP via
  ``PADDLE_TPU_HEARTBEAT_FILE`` — a wedged engine stops the beacon and
  the pool's watchdog SIGKILLs + relaunches.
- **Live SLO export** (PR 13): ``--metrics-port`` serves this
  replica's ``/metrics``; the router scrapes-and-merges every
  replica's endpoint into the fleet exposition the autoscaler reads.
- **Chaos** (``replica_kill`` injector): fired from the engine's step
  boundary, so an inherited ``PADDLE_TPU_CHAOS`` spec kills this
  replica mid-decode deterministically.

Protocol (one JSON object per line):

parent -> worker
    ``{"op": "submit", "rid", "prompt", "max_new_tokens", "eos_id",
    "arrival_t", "trace", "tenant"}`` | ``{"op": "cancel", "rid"}`` |
    ``{"op": "drain"}``
    | ``{"op": "stats"}`` | ``{"op": "stop"}``
worker -> parent
    ``{"t": "ready", "replica", "pid", "metrics_port", "compiles",
    "warmed"}`` | ``{"t": "done", "rid", "state", "tokens", ...}`` |
    ``{"t": "rejected", "rid", "reason"}`` | ``{"t": "drained"}`` |
    ``{"t": "stats", ...}`` | ``{"t": "bye"}``

Timestamps use the WALL clock (``time.time``): the router lives in
another process, and monotonic clocks don't compare across processes.
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time

__all__ = ["main"]


def _emit(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _reader(q):
    for line in sys.stdin:
        q.put(line)
    q.put(None)   # EOF: the parent is gone — shut down


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--replica-id", type=int, required=True)
    ap.add_argument("--vocab-size", type=int, default=32)
    ap.add_argument("--num-heads", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--pages", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--token-budget", type=int, default=256)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="-1 disables the exporter, 0 = ephemeral")
    ap.add_argument("--warm", action="store_true",
                    help="compile/hydrate every bucket before ready")
    args = ap.parse_args(argv)

    from ...obs import journal as _journal
    from ...obs.export import MetricsExporter
    from ...resilience.elastic import Heartbeat
    from ..engine import ServeEngine, TinyLM
    from ..kv_cache import PagedKVCache
    from ..scheduler import CANCELLED, Scheduler

    if _journal.ACTIVE is not None:
        # per-record flush (the elastic_run drill workers' discipline):
        # a replica_kill is os._exit — no atexit, no flush — so a
        # buffered journal would lose the kill incarnation's compile
        # and request records; and the drill reads the RELAUNCHED
        # incarnation's records while this worker is still serving
        _journal.ACTIVE.flush_every = 1

    hb = Heartbeat.from_env()
    hb.beat(0)

    model = TinyLM(vocab_size=args.vocab_size,
                   num_heads=args.num_heads, head_dim=args.head_dim,
                   seed=args.seed)
    cache = PagedKVCache(args.pages, args.page_size, args.num_heads,
                         args.head_dim, max_seq_len=args.max_seq_len)
    eng = ServeEngine(
        model, cache,
        scheduler=Scheduler(cache, token_budget=args.token_budget,
                            clock=time.time),
        replica_id=args.replica_id)
    warmed = eng.warm(max_batch=args.max_batch) if args.warm else 0
    hb.beat(0)

    exporter = None
    port = None
    if args.metrics_port >= 0:
        exporter = MetricsExporter(engines=[eng],
                                   port=args.metrics_port)
        port = exporter.start()

    _emit({"t": "ready", "replica": args.replica_id,
           "pid": os.getpid(), "metrics_port": port,
           "warmed": warmed, "compiles": eng._compiles})

    cmds = queue.Queue()
    threading.Thread(target=_reader, args=(cmds,), daemon=True).start()

    reqs = {}          # rid -> engine Request
    done_mark = 0
    draining = False
    drained_said = False
    stop = False
    while not stop:
        # drain every pending command first: submits must join the
        # NEXT engine step, not wait a full idle tick
        try:
            block = eng.scheduler.idle  # nothing to decode: wait
            line = cmds.get(block=block, timeout=0.05 if block
                            else None)
        except queue.Empty:
            line = False
        while line is not False:
            if line is None:
                stop = True
                break
            line = line.strip()
            if line:
                try:
                    msg = json.loads(line)
                except ValueError:
                    msg = {}
                op = msg.get("op")
                if op == "submit":
                    rid = msg.get("rid")
                    if draining:
                        _emit({"t": "rejected", "rid": rid,
                               "reason": "draining"})
                    else:
                        try:
                            reqs[rid] = eng.submit(
                                msg["prompt"],
                                max_new_tokens=msg.get(
                                    "max_new_tokens", 16),
                                rid=rid, eos_id=msg.get("eos_id"),
                                arrival_t=msg.get("arrival_t"),
                                trace=msg.get("trace"),
                                tenant=msg.get("tenant"))
                        except ValueError as e:
                            _emit({"t": "rejected", "rid": rid,
                                   "reason": str(e)})
                elif op == "cancel":
                    r = reqs.get(msg.get("rid"))
                    if r is not None:
                        eng.cancel(r)
                        if r.state == CANCELLED:
                            _emit(_done_record(r))
                            reqs.pop(r.rid, None)
                elif op == "drain":
                    draining = True
                elif op == "stats":
                    _emit({"t": "stats", **eng.stats()})
                elif op == "stop":
                    stop = True
                    break
            try:
                line = cmds.get_nowait()
            except queue.Empty:
                break
        if stop:
            break
        if not eng.scheduler.idle:
            eng.step()   # fires replica_kill chaos at its boundary
        hb.beat(eng._steps)
        # report completions in finish order
        fin = eng.finished
        while done_mark < len(fin):
            r = fin[done_mark]
            done_mark += 1
            _emit(_done_record(r))
            reqs.pop(r.rid, None)
        if draining and not drained_said and eng.scheduler.idle \
                and not reqs:
            drained_said = True
            _emit({"t": "drained", "replica": args.replica_id})
    if exporter is not None:
        exporter.stop()
    if _journal.ACTIVE is not None:
        # final per-tenant usage truth for this incarnation: the
        # device-ns telescoping and page-second closure land in the
        # rank journal, so the fleet rollup (obs.fleet.tenant_summary)
        # and the drill can assert them post-mortem. A chaos-killed
        # incarnation (os._exit) never reaches here — its stranded
        # requests' usage re-accrues on whichever replica re-serves
        # them.
        from ...obs import usage as _usage

        _journal.ACTIVE.event("tenant.usage",
                              **_usage.engine_tenant_usage(eng))
    _emit({"t": "bye", "replica": args.replica_id,
           "steps": eng._steps})
    return 0


def _done_record(r):
    return {"t": "done", "rid": r.rid, "state": r.state,
            "tokens": list(r.generated), "arrival_t": r.arrival_t,
            "admit_t": r.admit_t, "first_token_t": r.first_token_t,
            "finish_t": r.finish_t, "preemptions": r.preemptions,
            "prompt_tokens": len(r.prompt)}


if __name__ == "__main__":
    sys.exit(main())
