"""ServeEngine: continuous-batching generation over the paged KV cache.

The serving data plane. Each ``step()`` takes one ``Scheduler`` batch
and drives it through two compiled executables:

- **prefill** (one per context-length bucket): encode a newly admitted
  (or preemption-resumed) request's context, scatter its K/V into the
  pages the scheduler allocated, and emit the first generated token —
  the TTFT token.
- **decode** (one per batch-size bucket): for every in-flight request,
  embed its newest token, append that token's K/V to its pages, run
  the ragged ``paged_decode_attention`` kernel across the whole mixed
  batch, and emit each request's next token. The K/V pools are
  **donated** through this step (``donate_argnums``), so the pool
  buffer updates in place in HBM every step — ``tools/perf_gate.py``
  asserts the ``input_output_alias`` on the compiled HLO.

Decode semantics follow ``inference.decoder.greedy_search`` (argmax
continuation, EOS stop, fixed ``max_new_tokens`` cap); a ``sample_fn``
swaps the token choice (the beam analog lives in ``inference.decoder``
— beams multiply KV pages per request and stay out of the continuous
batch). Cache pressure reuses the resilience machinery end to end:
page exhaustion surfaces as ``CachePressureError`` (a
``TransientError``), and the engine relieves it inside
``resilience.policy.retry_call`` — preempting the scheduler's chosen
victim per retry under the policy's bounded budget, so every relief
attempt ticks ``resilience.retries`` and journals the same
``resilience.retry`` events a training guard would.

Per-request observability: lifecycle span markers
(``serving.request.{admit,first_token,finish}``), ``serving.*``
metrics (queue-depth gauge; TTFT/TPOT/e2e latency histograms with
p50/p99), and — when a run journal is active — one ``request`` record
per finished request (arrival/admit/first-token/finish timestamps,
pages held, preemptions) that ``tools/run_report.py`` summarizes.
All hooks follow the established zero-overhead contract: inactive
journal = one None check.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref

import numpy as np

from ..obs import journal as _journal
from ..obs import lockdep as _lockdep
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs import usage as _usage
from ..resilience import inject as _inject
from ..resilience.policy import RecoveryPolicy, retry_call
from .kv_cache import (CachePressureError, PagedKVCache,
                       PageAllocationError, write_tokens)
from .scheduler import CANCELLED, FINISHED, RUNNING, Request, Scheduler

__all__ = ["ServeEngine", "TinyLM", "live_engines", "request_phases",
           "preempt_loss_ms"]

# process-wide replica registry: every ServeEngine registers a weakref
# at construction, so the SLO exporter (obs.export.MetricsExporter with
# no explicit engine list) discovers every live replica in the process
# without any wiring. Weak by design — the registry must never keep a
# replaced replica (and its donated KV pools) alive.
_ENGINES_LOCK = _lockdep.lock("serving.engines")
_ENGINES: list = []
_REPLICA_IDS = itertools.count()


def live_engines():
    """Every ServeEngine constructed in this process and still alive,
    oldest first — the default scrape set for ``obs.export``."""
    out = []
    with _ENGINES_LOCK:
        keep = []
        for ref in _ENGINES:
            eng = ref()
            if eng is not None:
                keep.append(ref)
                out.append(eng)
        _ENGINES[:] = keep
    return out

# latency buckets: sub-ms CPU toy decode through multi-second cold
# prefill-compiles; +inf overflow implicit
_LAT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                250.0, 500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)
_M_TTFT = _metrics.histogram("serving.ttft_ms", _LAT_BUCKETS)
_M_TPOT = _metrics.histogram("serving.tpot_ms", _LAT_BUCKETS)
_M_E2E = _metrics.histogram("serving.e2e_ms", _LAT_BUCKETS)
_M_STEP = _metrics.histogram("serving.step_ms", _LAT_BUCKETS)
_M_TOKENS = _metrics.counter("serving.tokens_generated")
_M_FINISHED = _metrics.counter("serving.requests_finished")
_M_CANCELLED = _metrics.counter("serving.requests_cancelled")

_DECODE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return n


def _len_bucket(n, floor):
    """Context-length bucket for prefill: next power of two (>= the
    page size). Unlike batch sizes, context lengths are unbounded —
    a fixed table would compile one executable per distinct length
    past its cap (and every preemption-resume depth is a distinct
    length); powers of two bound the cache at log2(max_seq_len)."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return b


class TinyLM:
    """A deterministic one-layer attention LM — the built-in serving
    model for tests and ``tools/serve_bench.py`` (the stand-in for the
    Gemma-class decoder of arXiv 2605.25645's comparison). Tied
    embeddings, one causal attention layer with residual, weights
    drawn from a seeded RNG so every run replays bitwise.

    ``reference_generate`` is the dense oracle: step-by-step greedy
    decode with a contiguous (unpaged) KV history — the engine's
    paged continuous-batching output is pinned token-for-token
    against it.
    """

    def __init__(self, vocab_size=32, num_heads=2, head_dim=8, seed=0):
        import jax.numpy as jnp

        self.vocab_size = int(vocab_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.embed_dim = self.num_heads * self.head_dim
        rng = np.random.RandomState(seed)
        E = self.embed_dim

        def w(*shape):
            return jnp.asarray(
                rng.randn(*shape).astype(np.float32) / np.sqrt(shape[0]))

        self.embedding = w(self.vocab_size, E)
        self.wq, self.wk, self.wv, self.wo = w(E, E), w(E, E), w(E, E), \
            w(E, E)

    def qkv(self, token_ids):
        """(N,) ids -> (emb (N,E), q/k/v (N,H,D))."""
        import jax.numpy as jnp

        emb = jnp.take(self.embedding, token_ids, axis=0)
        N = emb.shape[0]
        shp = (N, self.num_heads, self.head_dim)
        return (emb, (emb @ self.wq).reshape(shp),
                (emb @ self.wk).reshape(shp),
                (emb @ self.wv).reshape(shp))

    def head(self, attn, emb):
        """attention out (N,H,D) + residual -> logits (N,V) (tied)."""
        out = attn.reshape(emb.shape) @ self.wo + emb
        return out @ self.embedding.T

    def reference_generate(self, prompt, max_new_tokens, eos_id=None):
        """Dense greedy decode (contiguous KV, no paging): the oracle."""
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import dense_decode_reference

        ctx = [int(t) for t in prompt]
        for _ in range(max_new_tokens):
            ids = jnp.asarray(np.asarray(ctx, np.int32))
            emb, q, k, v = self.qkv(ids)
            attn = dense_decode_reference(
                q[-1:], k[None], v[None])[0]           # (1,H,D)
            logits = self.head(attn[None], emb[-1:])
            nxt = int(jnp.argmax(logits[0]))
            ctx.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return ctx[len(prompt):]


class ServeEngine:
    """Continuous-batching serve loop over a model + paged KV cache.

    >>> eng = ServeEngine(TinyLM(), PagedKVCache(64, 8, 2, 8))
    >>> r = eng.submit([3, 1, 4], max_new_tokens=8)
    >>> eng.run()                       # until idle
    >>> r.generated

    Threading contract: ``step()``/``run()`` belong to ONE serve-loop
    thread. ``submit()`` and ``cancel()`` are safe from other threads
    (scheduler and cache state are lock-protected); a cancel landing
    while its request is inside the current step's batch takes effect
    at the next step boundary.
    """

    def __init__(self, model, cache, scheduler=None, policy=None,
                 sample_fn=None, interpret=None, clock=None,
                 aot_cache_dir=None, replica_id=None):
        self.model = model
        self.cache = cache
        if cache.num_heads != model.num_heads or \
                cache.head_dim != model.head_dim:
            raise ValueError(
                f"cache geometry ({cache.num_heads}h x {cache.head_dim}d)"
                f" != model ({model.num_heads}h x {model.head_dim}d)")
        if cache.num_layers != 1:
            # the engine's compiled steps read/write layer 0 only; a
            # multi-layer pool would silently waste HBM on layers the
            # engine never touches (the allocator keeps the layer axis
            # for models driving the kernel directly)
            raise ValueError(
                f"ServeEngine drives single-layer models; got a "
                f"num_layers={cache.num_layers} pool")
        if scheduler is not None and scheduler.cache is not cache:
            raise ValueError(
                "scheduler wraps a different PagedKVCache than the one "
                "passed to ServeEngine — pages would allocate in one "
                "pool and be read from the other")
        self.scheduler = scheduler or Scheduler(
            cache, clock=clock if clock is not None else time.monotonic)
        if clock is not None and scheduler is not None:
            raise ValueError("pass clock via the Scheduler when you "
                             "construct one yourself")
        self.clock = self.scheduler.clock
        self.policy = policy or RecoveryPolicy(max_retries=3,
                                               sleep=lambda s: None)
        self.sample_fn = sample_fn
        if interpret is None:
            from ..ops import pallas as _pallas

            interpret = _pallas.auto_interpret()
        self._interpret = bool(interpret)
        self._decode_fns = {}    # bucket -> jitted step
        self._prefill_fns = {}   # length bucket -> jitted prefill
        # AOT executable cache (runtime.aot): a replica constructed
        # with aot_cache_dir= (or under PADDLE_TPU_AOT_CACHE /
        # configure()) hydrates its prefill/decode buckets from disk
        # instead of paying XLA compile per bucket on first traffic
        self._aot_cache_dir = aot_cache_dir
        self._compiles = 0
        self._dispatches = 0
        self.finished = []       # completed Request objects, in order
        self._steps = 0
        self._last_emit = {}     # rid -> last token emission time
        # serializes step() against cancel(): a cancel landing while
        # its request is inside the current batch must wait for the
        # step boundary, or the freed rid KeyErrors the batch build
        # Lock order inside a replica: engine.step -> scheduler ->
        # cache (lockdep-checked under PADDLE_TPU_LOCKDEP)
        self._step_lock = _lockdep.rlock("serving.engine.step")
        # SLO-export identity: stable per process, rides the exporter's
        # replica="N" label so multi-replica scrapes stay attributable.
        # A fleet launcher passes the FLEET-assigned id instead — the
        # per-process counter restarts at 0 in every worker process, so
        # two replicas' scrapes would otherwise collide on replica="0"
        self.replica_id = next(_REPLICA_IDS) if replica_id is None \
            else int(replica_id)
        # per-tenant device-second attribution (obs.usage): charged
        # from step() always-on (plain int/dict arithmetic, the same
        # cost class as the step_ms histogram observe); read pull-only
        self.usage = _usage.UsageMeter(replica_id=self.replica_id)
        # requests that finished mid-step: their journal records are
        # deferred to the end of step() so the pass's device-second
        # charge is already in request_ns when the record is written
        self._finished_this_step = []
        with _ENGINES_LOCK:
            _ENGINES.append(weakref.ref(self))

    # -- intake --------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, rid=None, eos_id=None,
               arrival_t=None, trace=None, tenant=None):
        req = Request(prompt=list(prompt), max_new_tokens=max_new_tokens,
                      rid=rid, eos_id=eos_id, arrival_t=arrival_t,
                      trace=trace, tenant=tenant)
        if any(not 0 <= t < self.model.vocab_size for t in req.prompt):
            raise ValueError("prompt token out of vocab range")
        # the deepest context this request can reach is
        # prompt + max_new_tokens - 1 (the final token never needs a
        # slot): reject what can NEVER fit, at the door. An oversize
        # request admitted anyway would ValueError mid-decode (killing
        # the loop for every other in-flight request); a
        # budget-unschedulable one would block the FIFO head forever —
        # a silent stall that starves everything queued behind it
        worst = len(req.prompt) + int(max_new_tokens) - 1
        if worst > self.cache.max_seq_len:
            raise ValueError(
                f"request needs up to {worst} cached tokens > "
                f"max_seq_len {self.cache.max_seq_len}")
        if worst > self.scheduler.token_budget:
            raise ValueError(
                f"request may re-prefill up to {worst} tokens > "
                f"token_budget {self.scheduler.token_budget}: it could "
                "never be (re-)admitted")
        return self.scheduler.submit(req)

    def cancel(self, request):
        """Tear down a request wherever it is (the chaos-kill path):
        pages freed, journaled as cancelled — alloc==free still holds.
        No-op on an already-terminal request: the cancel-vs-complete
        race must not double-journal or rewrite FINISHED state. Blocks
        until any in-flight step() completes (the documented next-
        step-boundary semantics) — tearing pages out from under the
        running batch would KeyError the serve loop."""
        with self._step_lock:
            if request.state in (FINISHED, CANCELLED):
                return
            self.scheduler.finish(request, state=CANCELLED)
            self._last_emit.pop(request.rid, None)
            _M_CANCELLED.inc()
            self._journal_request(request)

    # -- compiled steps ------------------------------------------------------
    def _get_prefill_fn(self, bucket_len):
        fn = self._prefill_fns.get(bucket_len)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import dense_decode_reference

        model, page_size = self.model, self.cache.page_size
        n_page_slots = -(-bucket_len // page_size)
        interpret = self._interpret  # noqa: F841 (dense prefill)

        def prefill(k_pages, v_pages, tokens, length, page_ids):
            # tokens (Lb,) padded; length () true context length;
            # page_ids (n_page_slots,) the sequence's pages (null-padded)
            emb, q, k, v = model.qkv(tokens)
            pos = jnp.arange(bucket_len)
            live = pos < length
            pid = jnp.where(live, page_ids[pos // page_size], 0)
            off = pos % page_size
            k_pages, v_pages = write_tokens(
                k_pages, v_pages, k, v, pid, off)
            qlast = jnp.take(q, length - 1, axis=0)        # (H, D)
            attn = dense_decode_reference(
                qlast[None], k[None], v[None],
                lengths=length[None])[0]                   # (H, D)
            logits = model.head(
                attn[None], jnp.take(emb, length - 1, axis=0)[None])[0]
            return logits, k_pages, v_pages

        fn = jax.jit(prefill, donate_argnums=(0, 1))
        struct = jax.ShapeDtypeStruct
        i32 = np.dtype(np.int32)
        pool_s = struct(
            (self.cache.num_layers, self.cache.num_pages,
             self.cache.page_size, self.cache.num_heads,
             self.cache.head_dim), np.dtype(self.cache.dtype))
        fn, aot_info = self._maybe_aot(
            fn, (pool_s, pool_s, struct((bucket_len,), i32),
                 struct((), i32), struct((n_page_slots,), i32)),
            "serve_prefill")
        self._prefill_fns[bucket_len] = fn
        self._compiles += 1
        self._journal_compile("prefill", bucket=bucket_len,
                              aot_info=aot_info)
        return fn

    def _get_decode_fn(self, bucket, width=None):
        # table width is bucketed by the batch's ACTUAL max pages, not
        # the pool-wide maximum: the kernel grid (and the page DMAs it
        # drives) is (B, width), so a pool-wide table would make every
        # token's K/V traffic O(pool) instead of O(context)
        W = min(width or self.cache.table_width, self.cache.table_width)
        key = (bucket, W)
        entry = self._decode_fns.get(key)
        if entry is not None:
            return entry
        import jax
        import jax.numpy as jnp

        from ..ops.pallas.paged_attention import paged_decode_attention

        model, interpret = self.model, self._interpret

        def decode(k_pages, v_pages, tokens, tables, lengths,
                   slot_pages, slot_offs):
            emb, q, k, v = model.qkv(tokens)
            k_pages, v_pages = write_tokens(
                k_pages, v_pages, k, v, slot_pages, slot_offs)
            attn = paged_decode_attention(
                q, k_pages[0], v_pages[0], tables, lengths,
                interpret=interpret)
            return model.head(attn, emb), k_pages, v_pages

        fn = jax.jit(decode, donate_argnums=(0, 1))
        struct = jax.ShapeDtypeStruct
        pool_s = struct(
            (self.cache.num_layers, self.cache.num_pages,
             self.cache.page_size, self.cache.num_heads,
             self.cache.head_dim), np.dtype(self.cache.dtype))
        i32 = np.dtype(np.int32)
        entry = _DecodeEntry(fn, (
            pool_s, pool_s, struct((bucket,), i32),
            struct((bucket, W), i32), struct((bucket,), i32),
            struct((bucket,), i32), struct((bucket,), i32)), bucket, W)
        entry.fn, aot_info = self._maybe_aot(
            entry.fn, entry.arg_structs, "serve_decode")
        self._decode_fns[key] = entry
        self._compiles += 1
        self._journal_compile("decode", bucket=bucket, table_width=W,
                              aot_info=aot_info)
        return entry

    def warm(self, max_batch=8):
        """Compile (or AOT-hydrate) EVERY bucketed step this engine can
        reach up front: all prefill context-length buckets (the
        ``_len_bucket`` power-of-two ladder from ``page_size`` to
        ``max_seq_len``) and every decode (batch-bucket, table-width)
        pair up to ``max_batch`` lanes. With an AOT cache configured
        this is the replica scale-up story: the FIRST incarnation pays
        XLA once and publishes, every later replica (or relaunch)
        hydrates the whole set from disk before its first request — the
        fleet drill asserts a relaunched replica journals zero
        ``via=="xla"`` compiles. Returns the number of entries warmed.
        (Without a cache the jitted steps still compile lazily on first
        dispatch — warming would build jit wrappers, not executables.)"""
        n = 0
        blen = self.cache.page_size
        while True:
            self._get_prefill_fn(_len_bucket(blen, self.cache.page_size))
            n += 1
            if blen >= self.cache.max_seq_len:
                break
            blen *= 2
        # reachable table widths are _len_bucket(pages, 1) clamped to
        # the pool-wide maximum — enumerate exactly that set
        widths, w = [], 1
        while w < self.cache.table_width:
            widths.append(w)
            w *= 2
        widths.append(self.cache.table_width)
        for b in _DECODE_BUCKETS:
            if b > max(int(max_batch), 1):
                break
            for w in widths:
                self._get_decode_fn(b, width=w)
                n += 1
        return n

    def decode_entry(self, bucket=1):
        """The compiled decode step as a perf-gate entry (``fn`` +
        ``arg_structs``): ``tools/perf_gate.check_entry`` lowers it and
        asserts the donated KV pool aliases."""
        return self._get_decode_fn(_bucket(bucket, _DECODE_BUCKETS))

    # -- the serve loop ------------------------------------------------------
    def step(self):
        """One engine iteration: schedule, prefill admissions, decode
        the running set, retire finished requests. Returns the Batch
        served (falsy when idle)."""
        with self._step_lock:   # cancel() waits for the step boundary
            if _inject.ACTIVE and "replica_kill" in _inject.ACTIVE:
                # serve-loop chaos boundary (the elastic.fire_step_chaos
                # twin): lets the fleet drill kill THIS replica mid-step,
                # gated on serve-step count + replica id. Inactive cost:
                # one empty-dict truthiness test
                _inject.fire("replica_kill", step=self._steps + 1,
                             rank=self.replica_id)
            t0 = self.clock()
            batch = self.scheduler.schedule()
            if not batch:
                return batch
            if _journal.ACTIVE is not None and batch.decodes:
                # reqtrace decode-step mark: which requests decoded at
                # which engine clock — the per-step resolution the
                # assembled timelines anchor decode progress on
                _journal.ACTIVE.event(
                    "req.decode_mark", at=t0, step=self._steps + 1,
                    replica=self.replica_id,
                    rids=[r.rid for r in batch.decodes])
            try:
                with _trace.span("serving.step",
                                 prefills=len(batch.prefills),
                                 decodes=len(batch.decodes)):
                    for req in batch.prefills:
                        p0 = self.clock()
                        self._prefill_one(req)
                        self.usage.charge_prefill(req,
                                                  self.clock() - p0)
                    if batch.decodes:
                        d0 = self.clock()
                        survivors = self._decode_batch(
                            [r for r in batch.decodes
                             if r.state == RUNNING])
                        # the span splits across the lanes that
                        # actually decoded; an all-preempted pass
                        # charges nobody
                        self.usage.charge_decode(survivors,
                                                 self.clock() - d0)
            finally:
                # journal finishes only now: the pass's charge is in
                # request_ns, so the record's device_ns is final
                for req in self._finished_this_step:
                    self._journal_request(req)
                del self._finished_this_step[:]
            self._steps += 1
            step_ms = (self.clock() - t0) * 1e3
            _M_STEP.observe(step_ms)
            return batch

    def run(self, max_steps=None):
        """Serve until idle (or ``max_steps``). Returns steps taken."""
        steps = 0
        while not self.scheduler.idle:
            if max_steps is not None and steps >= max_steps:
                break
            if not self.step():
                break  # budget/pool gridlock: nothing schedulable
            steps += 1
        return steps

    # -- prefill -------------------------------------------------------------
    def _prefill_one(self, req):
        import jax.numpy as jnp

        ctx = req.context
        L = len(ctx)
        bucket_len = _len_bucket(L, self.cache.page_size)
        fn = self._get_prefill_fn(bucket_len)
        n_page_slots = -(-bucket_len // self.cache.page_size)
        tokens = np.zeros(bucket_len, np.int32)
        tokens[:L] = ctx
        pages = self.cache.page_table(req.rid)
        page_ids = np.zeros(n_page_slots, np.int32)
        page_ids[:len(pages)] = pages
        with _trace.span("serving.prefill", rid=req.rid, tokens=L):
            logits, k_pages, v_pages = fn(
                self.cache.k_pages, self.cache.v_pages,
                jnp.asarray(tokens), jnp.asarray(np.int32(L)),
                jnp.asarray(page_ids))
            self.cache.set_pools(k_pages, v_pages)
            self._dispatches += 1
            self._emit_token(req, logits, first=req.first_token_t is None)

    # -- decode --------------------------------------------------------------
    def _relieve_pressure(self, req):
        victim = self.scheduler.preempt_for(req)
        if victim is None:
            raise PageAllocationError(
                f"pool too small for {req.rid!r}: nothing to preempt")
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event("serving.preempt", victim=victim.rid,
                                  for_request=req.rid)

    def _decode_batch(self, decodes):
        import jax.numpy as jnp

        survivors = []
        for r in decodes:
            if r.state != RUNNING:
                continue  # preempted relieving an earlier lane
            if self.cache.length(r.rid) >= self.cache.max_seq_len:
                # belt-and-braces for requests submitted around
                # ``submit()`` (straight to the scheduler): finish
                # truncated instead of letting extend() ValueError
                # take down the whole serve loop
                self._finish(r)
                continue
            try:
                retry_call(lambda: self.scheduler.extend(r, 1),
                           self.policy, describe=f"extend {r.rid}",
                           before_retry=lambda: self._relieve_pressure(r))
                survivors.append(r)
            except (CachePressureError, PageAllocationError):
                # relief budget spent, or no other victim exists
                # (PageAllocationError from _relieve_pressure): r
                # itself yields its pages and requeues
                self.scheduler.preempt(r)
        # relieving a LATER lane may have preempted an earlier survivor
        # (it was the youngest running) — it no longer holds pages
        survivors = [r for r in survivors if r.state == RUNNING]
        if not survivors:
            return survivors
        n = len(survivors)
        bucket = _bucket(n, _DECODE_BUCKETS)
        rids = [r.rid for r in survivors]
        need = max(len(self.cache.page_table(rid)) for rid in rids)
        entry = self._get_decode_fn(bucket, _len_bucket(need, 1))
        W = entry.table_width
        tokens = np.zeros(bucket, np.int32)
        tokens[:n] = [r.context[-1] for r in survivors]
        tables = np.zeros((bucket, W), np.int32)
        tables[:n] = self.cache.padded_page_tables(rids, width=W)
        lengths = np.zeros(bucket, np.int32)
        lengths[:n] = [self.cache.length(rid) for rid in rids]
        slot_pages = np.zeros(bucket, np.int32)   # padding -> null page
        slot_offs = np.zeros(bucket, np.int32)
        sp, so = self.cache.write_slots(rids)
        slot_pages[:n], slot_offs[:n] = sp, so
        with _trace.span("serving.decode", batch=n, bucket=bucket):
            logits, k_pages, v_pages = entry.fn(
                self.cache.k_pages, self.cache.v_pages,
                jnp.asarray(tokens), jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(slot_pages),
                jnp.asarray(slot_offs))
            self.cache.set_pools(k_pages, v_pages)
            self._dispatches += 1
            logits = np.asarray(logits)    # ONE host sync per step
            for i, r in enumerate(survivors):
                self._emit_token(r, logits[i],
                                 first=r.first_token_t is None)
        return survivors

    # -- token plumbing ------------------------------------------------------
    def _choose(self, logits_row):
        if self.sample_fn is not None:
            return int(self.sample_fn(logits_row))
        return int(np.argmax(np.asarray(logits_row)))

    def _emit_token(self, req, logits_row, first=False):
        now = self.clock()
        tok = self._choose(logits_row)
        req.generated.append(tok)
        _M_TOKENS.inc()
        if first:
            req.first_token_t = now
            _M_TTFT.observe((now - req.arrival_t) * 1e3)
            with _trace.span("serving.request.first_token", rid=req.rid):
                pass
        else:
            _M_TPOT.observe((now - self._last_emit.get(req.rid, now))
                            * 1e3)
        self._last_emit[req.rid] = now
        if req.done:
            self._finish(req)

    def _finish(self, req):
        self.scheduler.finish(req, state=FINISHED)
        self._last_emit.pop(req.rid, None)
        self.finished.append(req)
        _M_FINISHED.inc()
        _M_E2E.observe((req.finish_t - req.arrival_t) * 1e3)
        with _trace.span("serving.request.finish", rid=req.rid,
                         tokens=len(req.generated)):
            pass
        # deferred: step() journals after the pass's usage charge lands
        self._finished_this_step.append(req)

    def _maybe_aot(self, fn, structs, kind):
        """Hydrate one jitted bucket step from the AOT executable cache
        (or compile eagerly + publish). ``(fn, None)`` unchanged when
        no cache is active or AOT failed — the lazy jit then compiles
        on first dispatch exactly as before."""
        from ..runtime import aot as _aot

        cache = _aot.resolve_cache(self._aot_cache_dir)
        if cache is None:
            return fn, None
        exe, info = _aot.load_or_compile(
            fn, structs, kind=kind, cache=cache,
            label=type(self.model).__name__)
        return (exe, info) if exe is not None else (fn, None)

    # -- observability -------------------------------------------------------
    def _journal_compile(self, kind, aot_info=None, **fields):
        if _journal.ACTIVE is not None:
            from ..runtime import aot as _aot

            _journal.ACTIVE.event("compile", source="serving",
                                  entry=kind, **fields,
                                  **_aot.provenance_fields(aot_info))

    def _journal_request(self, req):
        if _journal.ACTIVE is not None:
            extra = request_phases(req)
            if req.trace is not None:
                extra["trace"] = req.trace
            # chargeback extras: resolved tenant + the int-ns device /
            # page integrals, so obs.usage.rollup_requests rebuilds the
            # per-tenant table from journals alone, exact to the ns
            extra["tenant"] = req.tenant or _usage.DEFAULT_TENANT
            extra["device_ns"] = self.usage.request_ns.get(req.rid, 0)
            extra["page_ns"] = self.cache.closed_page_ns(req.rid)
            _journal.ACTIVE.record_request(
                rid=req.rid, state=req.state,
                arrival_t=req.arrival_t, admit_t=req.admit_t,
                first_token_t=req.first_token_t, finish_t=req.finish_t,
                prompt_tokens=len(req.prompt),
                output_tokens=len(req.generated),
                pages_peak=req.pages_peak,
                preemptions=req.preemptions, replica=self.replica_id,
                **extra)

    def stats(self):
        """Engine + pool + latency snapshot (plain data). Latency
        percentiles are computed from THIS engine's finished requests
        (exact, per-instance) — the ``serving.*`` histograms remain
        the process-wide view and would misattribute other engines'
        samples here."""
        from ..obs.metrics import exact_percentile

        snap = {
            "steps": self._steps, "compiles": self._compiles,
            "dispatches": self._dispatches,
            "finished": len(self.finished),
            "queue_depth": self.scheduler.queue_depth,
            "running": len(self.scheduler.running),
            "preemptions": self.scheduler.preemptions,
            "kv": self.cache.stats(),
            "usage": self.usage.snapshot(),
        }
        fin = list(self.finished)
        lat = {
            "ttft_ms": [(r.first_token_t - r.arrival_t) * 1e3
                        for r in fin if r.first_token_t is not None],
            "tpot_ms": [(r.finish_t - r.first_token_t) * 1e3 /
                        (len(r.generated) - 1)
                        for r in fin if len(r.generated) > 1
                        and r.first_token_t is not None],
            "e2e_ms": [(r.finish_t - r.arrival_t) * 1e3 for r in fin
                       if r.finish_t is not None],
        }
        for name, xs in lat.items():
            if xs:
                snap[name] = {"count": len(xs),
                              "p50": exact_percentile(xs, 50),
                              "p99": exact_percentile(xs, 99)}
        # phase attribution sums over finished requests (the numerators
        # of the per-replica phase-share gauges obs.export publishes):
        # queue (arrival->admit) + prefill + preempt + decode == e2e
        phases = {"queue": 0.0, "prefill": 0.0, "preempt": 0.0,
                  "decode": 0.0}
        for r in fin:
            if r.admit_t is not None and r.arrival_t is not None:
                phases["queue"] += (r.admit_t - r.arrival_t) * 1e3
            p = request_phases(r)
            phases["prefill"] += p.get("prefill_ms", 0.0)
            phases["preempt"] += p.get("preempt_ms", 0.0)
            phases["decode"] += p.get("decode_ms", 0.0)
        snap["phase_ms"] = phases
        return snap


def preempt_loss_ms(req):
    """Total wall time ``req`` spent preempted, in ms: every
    ``preempt_ts[i]`` pairs with ``resume_ts[i]`` (the scheduler stamps
    both), and a final unpaired preempt — the request was torn down
    while still PREEMPTED — pairs with ``finish_t``."""
    loss = 0.0
    for i, p in enumerate(req.preempt_ts):
        end = req.resume_ts[i] if i < len(req.resume_ts) else req.finish_t
        if end is not None:
            loss += (end - p) * 1e3
    return loss


def request_phases(req):
    """Engine-side phase decomposition of one terminal request (ms):
    ``prefill_ms`` (admit -> first token), ``preempt_ms`` (total time
    parked by preemption), ``decode_ms`` (first token -> finish, minus
    preemption loss). Together with the ``queue_ms`` the journal
    derives (arrival -> admit) the four telescope exactly to e2e —
    the attribution invariant ``obs.reqtrace`` builds on. Fields are
    emitted only when their stamps exist (a rejected request has no
    admission, a cancelled one may have no first token)."""
    out = {}
    if req.admit_t is not None and req.first_token_t is not None:
        out["prefill_ms"] = (req.first_token_t - req.admit_t) * 1e3
    if req.finish_t is not None:
        if req.preempt_ts:
            out["preempt_ms"] = preempt_loss_ms(req)
        if req.first_token_t is not None:
            out["decode_ms"] = (req.finish_t - req.first_token_t) * 1e3 \
                - out.get("preempt_ms", 0.0)
    return out


class _DecodeEntry:
    """A perf-gate-shaped cache entry (``fn`` + ``arg_structs``) for
    the engine's compiled decode step, mirroring the Executor's
    ``_Compiled`` contract that ``tools/perf_gate.entry_hlo`` reads."""

    def __init__(self, fn, arg_structs, bucket, table_width):
        self.fn = fn
        self.arg_structs = arg_structs
        self.bucket = bucket
        self.table_width = table_width
        self.examples_hint = bucket
