"""paddle_tpu.metrics — streaming evaluation metrics.

Ref: python/paddle/fluid/metrics.py (MetricBase at :58, Accuracy at :435,
Precision/Recall/Auc) and the paddle.metric 2.0 API. TPU-native notes:
``update`` accepts device arrays or Tensors and does its accumulation with
tiny host scalars — metrics never force a device sync inside a jitted
step; call them on fetched outputs.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "MetricBase", "Accuracy", "Precision", "Recall", "F1",
           "Auc", "MAE", "MSE", "RMSE", "CompositeMetric", "accuracy",
           "ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _np(x):
    if isinstance(x, Tensor):
        x = x._data
    return np.asarray(x)


class Metric:
    """ref: metrics.py:58 MetricBase / paddle.metric.Metric."""

    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    # fluid-era aliases
    def eval(self):
        return self.accumulate()

    def compute(self, pred, label, *args):
        """hapi hook: map raw model outputs to update() inputs."""
        return pred, label


MetricBase = Metric


def _topk_hits(pred, lab, k):
    """Top-k hit mask with fluid's top_k tie-breaking: ties at the k-th
    value resolve by smallest class index first (ref: fluid.layers.accuracy
    over the top_k op's stable CPU ordering). The label hits when its rank
    — classes scoring strictly higher, plus equal-scoring classes with a
    smaller index — is below k.

    Out-of-range labels (e.g. -100 ignore-index) and non-finite label
    scores are misses."""
    C = pred.shape[-1]
    valid = (lab >= 0) & (lab < C)
    safe = np.where(valid, lab, 0)
    lab_score = np.take_along_axis(pred, safe[:, None], axis=-1)
    ties_before = ((pred == lab_score)
                   & (np.arange(C)[None] < safe[:, None])).sum(axis=-1)
    rank = (pred > lab_score).sum(axis=-1) + ties_before
    return (rank < k) & valid & np.isfinite(lab_score[:, 0])


def accuracy(input, label, k=1):
    """Functional top-k accuracy (ref: fluid.layers.accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1)
    if pred.ndim == 1:
        hit = pred.reshape(-1).astype(np.int64) == lab
    else:
        hit = _topk_hits(pred, lab, k)
    return float(hit.mean())


class Accuracy(Metric):
    """ref: metrics.py:435 Accuracy (streaming top-k)."""

    def __init__(self, topk=1, name=None):
        super().__init__(name or "acc")
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk), np.int64)
        self.total = 0

    def update(self, pred, label):
        pred = _np(pred)
        lab = _np(label).reshape(-1)
        for i, k in enumerate(self.topk):
            self.correct[i] += int(_topk_hits(pred, lab, k).sum())
        self.total += lab.shape[0]
        return self.accumulate()

    def accumulate(self):
        if self.total == 0:
            return 0.0 if len(self.topk) == 1 else [0.0] * len(self.topk)
        accs = (self.correct / self.total).tolist()
        return accs[0] if len(self.topk) == 1 else accs


class Precision(Metric):
    """Binary precision over thresholded scores (ref: metrics.py Precision)."""

    def __init__(self, name=None, threshold=0.5):
        super().__init__(name or "precision")
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, pred, label):
        p = (_np(pred).reshape(-1) > self.threshold)
        l = _np(label).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fp += int((p & ~l).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return float(self.tp) / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None, threshold=0.5):
        super().__init__(name or "recall")
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, pred, label):
        p = (_np(pred).reshape(-1) > self.threshold)
        l = _np(label).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fn += int((~p & l).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return float(self.tp) / d if d else 0.0


class F1(Metric):
    def __init__(self, name=None, threshold=0.5):
        super().__init__(name or "f1")
        self._p = Precision(threshold=threshold)
        self._r = Recall(threshold=threshold)

    def reset(self):
        self._p.reset()
        self._r.reset()

    def update(self, pred, label):
        self._p.update(pred, label)
        self._r.update(pred, label)

    def accumulate(self):
        p, r = self._p.accumulate(), self._r.accumulate()
        return 2 * p * r / (p + r) if (p + r) else 0.0


class Auc(Metric):
    """ROC AUC via the reference's histogram-bucket method
    (ref: metrics.py Auc: num_thresholds stat buckets, trapezoid area)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, pred, label):
        p = _np(pred)
        if p.ndim == 2:  # (N, 2) softmax output: positive-class prob
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(label).reshape(-1).astype(bool)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[l], 1)
        np.add.at(self._neg, idx[~l], 1)

    def accumulate(self):
        # sweep thresholds high->low accumulating TP/FP counts
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        P = tp[-1]
        N = fp[-1]
        if P == 0 or N == 0:
            return 0.0
        tpr = np.concatenate([[0.0], tp / P])
        fpr = np.concatenate([[0.0], fp / N])
        return float(np.trapezoid(tpr, fpr))


class MAE(Metric):
    def __init__(self, name=None):
        super().__init__(name or "mae")
        self.reset()

    def reset(self):
        self.abs_sum = 0.0
        self.total = 0

    def update(self, pred, label):
        e = np.abs(_np(pred).reshape(-1) - _np(label).reshape(-1))
        self.abs_sum += float(e.sum())
        self.total += e.shape[0]

    def accumulate(self):
        return self.abs_sum / self.total if self.total else 0.0


class MSE(Metric):
    def __init__(self, name=None):
        super().__init__(name or "mse")
        self.reset()

    def reset(self):
        self.sq_sum = 0.0
        self.total = 0

    def update(self, pred, label):
        e = _np(pred).reshape(-1) - _np(label).reshape(-1)
        self.sq_sum += float((e * e).sum())
        self.total += e.shape[0]

    def accumulate(self):
        return self.sq_sum / self.total if self.total else 0.0


class RMSE(MSE):
    def __init__(self, name=None):
        super().__init__(name or "rmse")

    def accumulate(self):
        return float(np.sqrt(super().accumulate()))


class CompositeMetric(Metric):
    """ref: metrics.py CompositeMetric — fan one update to many metrics."""

    def __init__(self, *metrics, name=None):
        super().__init__(name or "composite")
        self._metrics = list(metrics)

    def add_metric(self, m):
        self._metrics.append(m)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, pred, label):
        for m in self._metrics:
            m.update(pred, label)

    def accumulate(self):
        return [m.accumulate() for m in self._metrics]


class ChunkEvaluator(Metric):
    """Streaming chunk-level precision/recall/F1 over IOB/IOE/IOBES tag
    sequences (ref: fluid.metrics.ChunkEvaluator + the chunk_eval op).
    ``update(pred, label, seq_length=None)`` accumulates chunk counts;
    ``accumulate()`` -> (precision, recall, f1)."""

    def __init__(self, chunk_scheme="IOB", num_chunk_types=1,
                 excluded_chunk_types=None, name=None):
        super().__init__(name or "chunk")
        self.chunk_scheme = chunk_scheme
        self.num_chunk_types = num_chunk_types
        self.excluded_chunk_types = excluded_chunk_types
        self.reset()

    def reset(self):
        self.n_infer = 0
        self.n_label = 0
        self.n_correct = 0

    def update(self, pred, label, seq_length=None):
        from ..ops.labeling import chunk_eval

        _, _, _, ni, nl, nc = chunk_eval(
            _np(pred), _np(label), self.chunk_scheme,
            self.num_chunk_types, seq_length=seq_length,
            excluded_chunk_types=self.excluded_chunk_types)
        self.n_infer += ni
        self.n_label += nl
        self.n_correct += nc
        return self.accumulate()

    def accumulate(self):
        p = self.n_correct / self.n_infer if self.n_infer else 0.0
        r = self.n_correct / self.n_label if self.n_label else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1


class EditDistance(Metric):
    """Streaming average edit distance (ref: fluid/metrics.py
    EditDistance). update() takes per-batch (distances, seq_num) as
    produced by ``ops.edit_distance``."""

    def __init__(self, name=None):
        super().__init__(name or "edit_distance")
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        d = _np(distances).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num if seq_num is not None else d.size)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data in EditDistance")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)

    def accumulate(self):
        return self.eval()


class DetectionMAP(Metric):
    """Mean average precision over padded detection outputs (ref:
    fluid/metrics.py DetectionMAP / detection_map op). update() takes
    per-image detections (label, score, x1, y1, x2, y2) rows — the
    multiclass_nms output — and gt rows (label, x1, y1, x2, y2);
    11-point or integral interpolation."""

    def __init__(self, overlap_threshold=0.5, map_type="11point",
                 evaluate_difficult=False, class_num=None, name=None):
        super().__init__(name or "detection_map")
        self.thr = overlap_threshold
        self.map_type = map_type
        self.reset()

    def reset(self):
        self._dets = []   # (cls, score, box, img_id)
        self._gts = []    # (cls, box, img_id)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = (a[2] - a[0]) * (a[3] - a[1]) + \
            (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gts):
        det = _np(detections)
        gt = _np(gts)
        for d in det.reshape(-1, 6):
            if d[0] >= 0:  # -1 pads
                self._dets.append((int(d[0]), float(d[1]),
                                   d[2:6].tolist(), self._img))
        for g in gt.reshape(-1, 5):
            if g[0] >= 0:
                self._gts.append((int(g[0]), g[1:5].tolist(), self._img))
        self._img += 1

    def eval(self):
        classes = sorted({c for c, *_ in self._gts})
        if not classes:
            raise ValueError("no ground truth in DetectionMAP")
        aps = []
        for c in classes:
            gts_c = [(b, i) for (cc, b, i) in self._gts if cc == c]
            dets_c = sorted([(s, b, i) for (cc, s, b, i) in self._dets
                             if cc == c], key=lambda x: -x[0])
            matched = set()
            tp = []
            for s, b, i in dets_c:
                best, best_j = 0.0, -1
                for j, (gb, gi) in enumerate(gts_c):
                    if gi == i and j not in matched:
                        o = self._iou(b, gb)
                        if o > best:
                            best, best_j = o, j
                if best >= self.thr and best_j >= 0:
                    matched.add(best_j)
                    tp.append(1)
                else:
                    tp.append(0)
            if not gts_c:
                continue
            cum_tp = np.cumsum(tp) if tp else np.zeros((0,))
            recall = cum_tp / len(gts_c)
            precision = cum_tp / np.maximum(
                np.arange(1, len(tp) + 1), 1) if tp else np.zeros((0,))
            if self.map_type == "11point":
                ap = 0.0
                for r in np.linspace(0, 1, 11):
                    pmax = precision[recall >= r].max() \
                        if (recall >= r).any() else 0.0
                    ap += pmax / 11.0
            else:  # integral
                ap = 0.0
                prev_r = 0.0
                for p_, r_ in zip(precision, recall):
                    ap += p_ * (r_ - prev_r)
                    prev_r = r_
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0

    def accumulate(self):
        return self.eval()
