"""Loss scaling (ref: python/paddle/fluid/contrib/mixed_precision/
decorator.py OptimizerWithMixedPrecision + amp_nn.py
update_loss_scaling, and paddle.amp.GradScaler).

fp16 needs dynamic loss scaling to keep small gradients from flushing to
zero; bf16 on TPU usually doesn't, but the machinery is here for parity
and for fp16 workloads. The scaler state is a pytree of scalars so the
whole update — scale, unscale, finite-check, conditional apply, scale
adjustment — compiles INTO the fused train step (no host sync per step;
the reference runs a separate update_loss_scaling op).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["StaticLossScaler", "DynamicLossScaler", "GradScaler"]


class StaticLossScaler:
    """Constant loss scale (ref: static loss_scaling in decorator.py)."""

    use_dynamic = False

    def __init__(self, init_loss_scaling=2.0 ** 15):
        self.loss_scaling = float(init_loss_scaling)

    def state(self):
        return {"scale": jnp.float32(self.loss_scaling),
                "good": jnp.int32(0)}

    def update_state(self, state, found_inf):
        return state


class DynamicLossScaler:
    """Grow scale after N clean steps; shrink on inf/nan
    (ref: update_loss_scaling in amp_nn.py)."""

    use_dynamic = True

    def __init__(self, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1):
        self.loss_scaling = float(init_loss_scaling)
        self.incr_ratio = float(incr_ratio)
        self.decr_ratio = float(decr_ratio)
        self.incr_every_n_steps = int(incr_every_n_steps)
        self.decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)

    def state(self):
        return {"scale": jnp.float32(self.loss_scaling),
                "good": jnp.int32(0), "bad": jnp.int32(0)}

    def update_state(self, state, found_inf):
        """Pure: new scaler state from the finite-check flag."""
        scale, good = state["scale"], state["good"]
        bad = state.get("bad", jnp.int32(0))
        good_new = jnp.where(found_inf, 0, good + 1)
        bad_new = jnp.where(found_inf, bad + 1, 0)
        grow = good_new >= self.incr_every_n_steps
        shrink = bad_new >= self.decr_every_n_nan_or_inf
        scale_new = jnp.where(
            shrink, jnp.maximum(scale * self.decr_ratio, 1.0),
            jnp.where(grow, scale * self.incr_ratio, scale))
        good_new = jnp.where(grow, 0, good_new)
        bad_new = jnp.where(shrink, 0, bad_new)
        return {"scale": scale_new.astype(jnp.float32),
                "good": good_new.astype(jnp.int32),
                "bad": bad_new.astype(jnp.int32)}


class GradScaler(DynamicLossScaler):
    """paddle.amp.GradScaler API over the dynamic scaler (eager path).

    For the fused path just pass the scaler to ``TrainStep(scaler=...)``;
    this class additionally supports the explicit eager protocol:
        scaled = scaler.scale(loss); scaled.backward()
        scaler.step(opt); scaler.update()
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        super().__init__(init_loss_scaling, incr_ratio, decr_ratio,
                         incr_every_n_steps, decr_every_n_nan_or_inf)
        self._enable = bool(enable)
        self.use_dynamic = bool(use_dynamic_loss_scaling)
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    is_use_dynamic_loss_scaling = lambda self: self.use_dynamic  # noqa: E731

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * float(self.loss_scaling)

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / float(self.loss_scaling)
        found = False
        for p in optimizer._param_groups:
            if p.grad is not None:
                g = p.grad._data * inv
                found = found or bool(jnp.any(~jnp.isfinite(g)))
                p.grad = Tensor(g, _internal=True)
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        del scaled_loss  # backward already ran on it
        self.step(optimizer)
        self.update()

    def notify_skip(self):
        """Record an externally-discarded step (resilience.GuardedStep's
        skip_step / rollback policies) as a found-inf event: the dynamic
        loss scale shrinks exactly as it would for an in-graph overflow,
        so guard-level and scaler-level skips stay on one state machine."""
        if not self._enable:
            return
        self._found_inf = True
        self.update()

    def update(self):
        if not self._enable:
            return
        if self.use_dynamic:
            if self._found_inf:
                self._good = 0
                self._bad = self._bad_py() + 1
                if self._bad >= self.decr_every_n_nan_or_inf:
                    self.loss_scaling = max(
                        self.loss_scaling * self.decr_ratio, 1.0)
                    self._bad = 0
            else:
                self._bad = 0
                self._good = self._good_py() + 1
                if self._good >= self.incr_every_n_steps:
                    self.loss_scaling *= self.incr_ratio
                    self._good = 0
        self._found_inf = False
        self._unscaled = False

    def _good_py(self):
        return getattr(self, "_good", 0)

    def _bad_py(self):
        return getattr(self, "_bad", 0)

    def state_dict(self):
        return {"scale": self.loss_scaling, "incr_ratio": self.incr_ratio,
                "decr_ratio": self.decr_ratio,
                "incr_every_n_steps": self.incr_every_n_steps,
                "good_steps": self._good_py(),
                "bad_steps": self._bad_py()}

    def load_state_dict(self, state):
        self.loss_scaling = float(state["scale"])
        self._good = int(state.get("good_steps", 0))
        self._bad = int(state.get("bad_steps", 0))
