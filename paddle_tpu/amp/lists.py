"""AMP op lists (ref: python/paddle/fluid/contrib/mixed_precision/
fp16_lists.py:20 AutoMixedPrecisionLists).

White: MXU-bound ops that are fast and safe in half precision.
Black: numerically sensitive ops kept in float32 (softmax/log/reductions/
norm statistics).
Everything else runs in whatever dtype its inputs arrive in (the
reference's gray list — type promotion decides).
"""
from __future__ import annotations

WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "dot", "linear",
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "sdpa",
}

BLACK_LIST = {
    "softmax", "log_softmax", "logsumexp",
    "cross_entropy_hard", "cross_entropy_soft", "nll_loss", "kl_div",
    "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "exp", "log", "log2", "log10", "log1p", "pow", "rsqrt",
    "mean", "sum", "prod", "std", "var",
    "layer_norm", "layer_norm_noaffine", "batch_norm", "group_norm",
    "instance_norm", "norm", "cosine_similarity", "erf", "softplus",
    "sigmoid_focal_loss", "ctc_loss",
}


class AutoMixedPrecisionLists:
    """ref: fp16_lists.py AutoMixedPrecisionLists — resolved white/black
    sets after applying user customization."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        both = set(custom_white_list or ()) & set(custom_black_list or ())
        if both:
            raise ValueError(f"ops {sorted(both)} in both custom lists")
        if custom_white_list:
            for op in custom_white_list:
                self.black_list.discard(op)
                self.white_list.add(op)
        if custom_black_list:
            for op in custom_black_list:
                self.white_list.discard(op)
                self.black_list.add(op)
