"""auto_cast: per-op mixed-precision dispatch.

Ref: python/paddle/fluid/contrib/mixed_precision/decorator.py:218 and
paddle.amp.auto_cast (dygraph amp_guard). TPU-native: instead of
rewriting a Program with cast ops, the eager dispatcher consults the
active amp state and casts op inputs *inside* the traced computation —
so the casts live in the vjp too, gradients arrive in the original param
dtype, and under jit XLA fuses every cast into the adjacent kernel (zero
copies on TPU; bf16 feeds the MXU directly).

O1: params stay f32, white-listed ops compute in half precision.
O2: `decorate` casts the whole model to half precision with f32 master
weights in the optimizer; black-listed ops still run f32.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from .lists import AutoMixedPrecisionLists

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "amp_stack"):
        _tls.amp_stack = []
    return _tls.amp_stack


class _AmpState:
    __slots__ = ("dtype", "lists")

    def __init__(self, dtype, lists):
        self.dtype = dtype
        self.lists = lists


def amp_state():
    """Innermost active auto_cast state, or None (consulted by dispatch)."""
    st = _stack()
    return st[-1] if st else None


def _cast_tree(x, dtype):
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) and \
            x.dtype != dtype:
        return x.astype(dtype)
    return x


def cast_op_inputs(name, arrays):
    """Apply the active amp policy to one op's input arrays (called from
    core.dispatch inside the differentiated function)."""
    state = amp_state()
    if state is None:
        return arrays
    if name in state.lists.white_list:
        return [_cast_tree(a, state.dtype) for a in arrays]
    if name in state.lists.black_list:
        return [_cast_tree(a, jnp.float32) for a in arrays]
    return arrays


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """ref: paddle.amp.auto_cast / fluid amp_guard.

    dtype: 'bfloat16' (TPU-native) or 'float16'.
    """
    if not enable:
        yield
        return
    if level not in ("O1", "O2"):
        raise ValueError(f"level must be O1 or O2, got {level}")
    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
    lists = AutoMixedPrecisionLists(custom_white_list, custom_black_list)
    state = _AmpState(jdtype, lists)
    _stack().append(state)
    try:
        yield
    finally:
        _stack().pop()


amp_guard = auto_cast  # fluid-era alias
