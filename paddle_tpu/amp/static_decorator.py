"""Static-graph mixed precision (fluid.contrib.mixed_precision surface).

ref: python/paddle/fluid/contrib/mixed_precision/decorator.py:218
``decorate`` + OptimizerWithMixedPrecision, fp16_utils.py rewrite_program.

The reference rewrites the ProgramDesc: inserts cast ops per the
white/black lists, scales the loss, and guards updates with the
check_finite_and_unscale / update_loss_scaling ops. Here the same three
pieces map onto the one-executable TPU design:

- list-driven casts are applied when the Executor interprets the program
  (``static_/executor.py`` honors ``program._amp_cfg``); XLA fuses the
  casts into the ops, so there is no separate cast pass to run;
- loss scaling, the finite check, the inf-guarded update, and the
  dynamic scale adjustment are appended as ordinary program ops by
  ``build_optimize_ops(amp_decorator=...)`` — the whole AMP train step
  still compiles to ONE fused executable.

bfloat16 is the TPU-native half type (same exponent range as f32), so
loss scaling is mathematically a no-op there — the machinery is still
real and exercised, and ``dtype='float16'`` gets the full protection.
"""
from __future__ import annotations

import jax.numpy as jnp

from .grad_scaler import DynamicLossScaler
from .lists import AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision"]


class OptimizerWithMixedPrecision:
    """Wraps an optimizer for static-mode AMP training
    (ref: decorator.py:40). Use through :func:`decorate`."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 dtype="bfloat16"):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._scaler = DynamicLossScaler(
            init_loss_scaling=init_loss_scaling, incr_ratio=incr_ratio,
            decr_ratio=decr_ratio, incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf)
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic = bool(use_dynamic_loss_scaling)
        self._dtype = dtype
        self._scaled_loss = None
        self._params_grads = None
        self._found_inf_name = None

    # -- reference accessors ------------------------------------------------
    def get_loss_scaling(self):
        """Current loss scale (host value, read from the scope)."""
        from ..static_.program import global_scope

        v = global_scope().find_var("@amp@scale")
        return float(v) if v is not None else self._init_loss_scaling

    def get_scaled_loss(self):
        return self._scaled_loss

    def get_lr(self):  # the Executor feeds @lr through this
        return self._optimizer.get_lr()

    # -- reference API: backward / apply_gradients / minimize ---------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        """Scale the loss, append grad ops, check-finite + unscale."""
        from ..static_.executor import append_amp_backward

        self._params_grads, self._found_inf_name = append_amp_backward(
            self, loss, parameter_list)
        return self._params_grads

    def apply_gradients(self, params_grads):
        from ..static_.executor import append_update_ops

        append_update_ops(self._optimizer, params_grads,
                          amp_decorator=self,
                          found_inf_name=self._found_inf_name)
        return []

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        self.apply_gradients(params_grads)
        return None, params_grads

    # -- pure rules used by the appended ops --------------------------------
    def check_and_unscale_rule(self, scale, *grads):
        """found_inf flag + grads/scale in f32 (master-grad flow; the
        update op casts to the param dtype)."""
        finite = jnp.asarray(True)
        for g in grads:
            finite &= jnp.all(jnp.isfinite(g))
        inv = jnp.float32(1.0) / scale.astype(jnp.float32)
        return (~finite,) + tuple(g.astype(jnp.float32) * inv for g in grads)

    def update_scaling_rule(self, scale, good, bad, found_inf):
        s = self._scaler.update_state(
            {"scale": scale, "good": good, "bad": bad}, found_inf)
        return s["scale"], s["good"], s["bad"]


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, dtype="bfloat16"):
    """ref: decorator.py:218 fluid.contrib.mixed_precision.decorate.

    Returns an :class:`OptimizerWithMixedPrecision` whose ``minimize``
    builds a loss-scaled, inf-guarded, list-casted train step. ``dtype``
    is a TPU-era extension (the reference is fp16-only): 'bfloat16'
    (default, native) or 'float16'.
    """
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dtype=dtype)
