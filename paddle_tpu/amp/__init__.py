"""paddle_tpu.amp — automatic mixed precision.

Ref: python/paddle/fluid/contrib/mixed_precision (decorator.py:218
``decorate``, fp16_lists.py:20 ``AutoMixedPrecisionLists``, amp_nn.py
dynamic loss scaling) and the paddle.amp 2.0 API. See autocast.py for the
TPU-native design (dispatch-level casts instead of program rewriting).
"""
from .autocast import auto_cast, amp_guard, amp_state, cast_op_inputs  # noqa: F401
from .lists import AutoMixedPrecisionLists, WHITE_LIST, BLACK_LIST  # noqa: F401
from .grad_scaler import (  # noqa: F401
    StaticLossScaler, DynamicLossScaler, GradScaler,
)

__all__ = [
    "auto_cast", "amp_guard", "decorate", "AutoMixedPrecisionLists",
    "StaticLossScaler", "DynamicLossScaler", "GradScaler",
]


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """ref: decorator.py:218 / paddle.amp.decorate.

    O2: cast model params to half precision; optimizers keep f32 master
    weights (multi_precision). O1: no param cast (auto_cast does the work).
    Returns (models, optimizers) with the same nesting the caller passed.
    """
    from ..nn.layer import Layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models or [])
    from ..optim.optimizer import Optimizer

    single_opt = isinstance(optimizers, Optimizer)
    opt_list = [optimizers] if single_opt else list(optimizers or [])

    if level == "O2":
        for m in model_list:
            m.astype(dtype)
        for o in opt_list:
            o._multi_precision = True
            if master_weight is not False:
                # refresh existing slots so masters materialize
                for p in o._param_groups:
                    if p.name in o._accumulators:
                        del o._accumulators[p.name]
    elif level != "O1":
        raise ValueError(f"level must be O1 or O2, got {level}")

    models_out = model_list[0] if single_model else model_list
    opts_out = opt_list[0] if single_opt else opt_list
    if optimizers is None:
        return models_out
    return models_out, opts_out
