"""Dataset commons (ref: python/paddle/dataset/common.py). The download
half is inert in this zero-egress environment; file utilities and the
converter (to the native record format) are real."""
from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "md5file", "download", "split", "fetch_all",
           "cluster_files_reader", "convert"]

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Zero-egress: only serves an already-present cached file."""
    d = os.path.join(DATA_HOME, module_name)
    path = os.path.join(d, save_name or url.split("/")[-1])
    if os.path.exists(path) and (not md5sum or md5file(path) == md5sum):
        return path
    raise RuntimeError(
        f"cannot download {url}: no network egress; place the file at "
        f"{path} (the dataset readers default to synthetic data instead)")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Shard a reader into pickle files of ``line_count`` samples."""
    import pickle

    dumper = dumper or pickle.dump
    buf, idx, written = [], 0, []

    def flush():
        nonlocal buf, idx
        if not buf:
            return
        name = suffix % idx
        with open(name, "wb") as f:
            dumper(buf, f)
        written.append(name)
        buf = []
        idx += 1

    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            flush()
    flush()
    return written


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Round-robin shard of pickle files across trainers."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        files = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(files):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample

    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Serialize a reader into the native crc-framed record files
    (runtime RecordWriter) in ``line_count`` chunks (the reference
    converts to recordio)."""
    import pickle

    from ..runtime import RecordWriter

    buf, idx, written = [], 0, []

    def flush():
        nonlocal buf, idx
        if not buf:
            return
        path = os.path.join(output_path, f"{name_prefix}-{idx:05d}")
        with RecordWriter(path) as w:
            for sample in buf:
                w.write(pickle.dumps(sample))
        written.append(path)
        buf = []
        idx += 1

    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            flush()
    flush()
    return written


def fetch_all():
    """ref: common.py:117 fetch_all — call every dataset module's
    fetch()."""
    import importlib
    import pkgutil

    import paddle_tpu.dataset as _ds

    for info in pkgutil.iter_modules(_ds.__path__):
        if info.name.startswith("_") or info.name in ("common", "image"):
            continue
        mod = importlib.import_module(f"paddle_tpu.dataset.{info.name}")
        if hasattr(mod, "fetch"):
            mod.fetch()
