"""IMDB-style movie-review sentiment (ref: python/paddle/dataset/
sentiment.py: get_word_dict(); train()/test() yield (ids, 0/1)).
Synthetic: class-conditioned Zipfian text."""
from ._synth import fetch  # noqa: F401
from ._synth import labeled_sentences, reader_creator

__all__ = ["train", "test", "get_word_dict"]

_VOCAB = 5000


def get_word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _make(n, seed):
    return reader_creator(labeled_sentences(n, _VOCAB, 8, 40, seed))


def train():
    return _make(1024, 70)


def test():
    return _make(256, 71)

