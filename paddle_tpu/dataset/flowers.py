"""Flowers-102 readers (ref: python/paddle/dataset/flowers.py:
train/test/valid yield ((3, 224, 224) float32 in [-1, 1], int label)).
Synthetic class-mean images generated LAZILY per sample (a materialized
512-sample split would hold ~300MB); mapper/cycle are honored."""
from ._synth import fetch  # noqa: F401
import numpy as np

__all__ = ["train", "test", "valid"]

_CLASSES = 102
_SHAPE = (3, 224, 224)


def _reader_creator(n, seed, mapper=None, cycle=False):
    def reader():
        # per-class means re-derived per class id on the fly: fold the
        # class into the seed instead of holding a (102, 3, 224, 224)
        # table
        rng = np.random.RandomState(seed)
        while True:
            for _ in range(n):
                y = int(rng.randint(0, _CLASSES))
                mean_rng = np.random.RandomState(seed * 1000003 + y)
                x = mean_rng.randn(*_SHAPE).astype("float32") + \
                    rng.randn(*_SHAPE).astype("float32") * 0.35
                sample = (np.tanh(x).astype("float32"), y)
                if mapper is not None:
                    sample = mapper(sample)
                yield sample
            if not cycle:
                return

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader_creator(512, 40, mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader_creator(128, 41, mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader_creator(128, 42, mapper, False)

