"""WMT16-style translation readers (ref: python/paddle/dataset/wmt16.py:
train(src_dict_size, trg_dict_size) yields (src_ids, trg_in, trg_next)).
Synthetic copy+shift task: the target is a deterministic function of the
source, so the transformer chapter trains to low loss. ids 0/1/2 =
<s>/<e>/<unk> like the reference."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator


def _make(n, seed, src_v, trg_v):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        L = rng.randint(4, 12)
        src = rng.randint(3, src_v, L)
        trg = (src % (trg_v - 3)) + 3  # learnable mapping
        src_ids = [0] + src.tolist() + [1]
        trg_ids = [0] + trg.tolist()
        trg_next = trg.tolist() + [1]
        out.append((src_ids, trg_ids, trg_next))
    return reader_creator(out)


def train(src_dict_size=1000, trg_dict_size=1000, tar_fname=None):
    return _make(1024, 14, src_dict_size, trg_dict_size)


def test(src_dict_size=1000, trg_dict_size=1000, tar_fname=None):
    return _make(128, 15, src_dict_size, trg_dict_size)


def validation(src_dict_size=1000, trg_dict_size=1000, tar_fname=None):
    """ref: wmt16.py validation()."""
    return _make(128, 16, src_dict_size, trg_dict_size)


def get_dict(lang, dict_size, reverse=False):
    """ref: wmt16.py get_dict(lang, ...) — synthetic ids are their own
    tokens; 0/1/2 are <s>/<e>/<unk>."""
    specials = {0: "<s>", 1: "<e>", 2: "<unk>"}
    d = {i: specials.get(i, f"{lang}_{i}") for i in range(dict_size)}
    if reverse:
        return d
    return {v: k for k, v in d.items()}

