"""MQ2007 learning-to-rank readers (ref: python/paddle/dataset/mq2007.py:
train/test with format in {pointwise, pairwise, listwise}).
Synthetic: 46-dim feature vectors whose relevance is a noisy linear
function, so rankers have signal to learn."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

__all__ = ["train", "test"]

_DIM = 46


def _queries(n_q, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(_DIM).astype("float32")
    out = []
    for _ in range(n_q):
        docs = rng.randint(5, 15)
        x = rng.randn(docs, _DIM).astype("float32")
        score = x @ w + rng.randn(docs) * 0.5
        rel = np.digitize(score, np.quantile(score, [0.5, 0.85]))
        out.append((x, rel.astype("int64")))
    return out


def _reader(n_q, seed, format):
    qs = _queries(n_q, seed)
    if format == "pointwise":
        samples = [(x[i], int(r[i])) for x, r in qs for i in range(len(r))]
    elif format == "pairwise":
        samples = []
        for x, r in qs:
            for i in range(len(r)):
                for j in range(len(r)):
                    if r[i] > r[j]:
                        samples.append((x[i], x[j]))
    elif format == "listwise":
        samples = [(x, r) for x, r in qs]
    else:
        raise ValueError(f"unknown format {format!r}")
    return reader_creator(samples)


def train(format="pairwise"):
    return _reader(64, 80, format)


def test(format="pairwise"):
    return _reader(16, 81, format)

