"""WMT14 en-fr readers (ref: python/paddle/dataset/wmt14.py:
train/test/gen(dict_size) yield (src_ids, trg_ids, trg_next);
get_dict(dict_size) -> (src_dict, trg_dict)). Synthetic parallel text."""
from ._synth import fetch  # noqa: F401
from ._synth import parallel_sentences, reader_creator

__all__ = ["train", "test", "gen", "get_dict"]


def _make(n, seed, dict_size):
    pairs = parallel_sentences(n, dict_size, dict_size, 4, 12, seed)
    samples = []
    for src, trg in pairs:
        trg_in = [0] + list(trg)          # <s>
        trg_next = list(trg) + [1]        # </e>
        samples.append((list(src), trg_in, trg_next))
    return reader_creator(samples)


def train(dict_size):
    return _make(1024, 60, dict_size)


def test(dict_size):
    return _make(128, 61, dict_size)


def gen(dict_size):
    return _make(64, 62, dict_size)


def get_dict(dict_size, reverse=True):
    words = {i: f"w{i}" for i in range(dict_size)}
    if reverse:
        return words, dict(words)
    inv = {v: k for k, v in words.items()}
    return inv, dict(inv)

