"""MovieLens readers (ref: python/paddle/dataset/movielens.py:
train()/test() yield (user_id, gender_id, age_id, job_id, movie_id,
category_ids, title_ids, rating)). Synthetic with a low-rank
user x movie preference structure the recommender can learn."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

_USERS, _MOVIES, _CATS, _TITLE_VOCAB = 944, 1683, 19, 512
_MAX_JOB = 20


def max_user_id():
    """ref API: paddle.dataset.movielens.max_user_id() -> int."""
    return _USERS


def max_movie_id():
    return _MOVIES


def max_job_id():
    return _MAX_JOB


def movie_categories():
    return {("c%d" % i): i for i in range(_CATS)}


def _make(n, seed):
    rng = np.random.RandomState(seed)
    uf = rng.randn(_USERS, 4)
    mf = rng.randn(_MOVIES, 4)
    out = []
    for _ in range(n):
        u = int(rng.randint(1, _USERS))
        m = int(rng.randint(1, _MOVIES))
        score = uf[u] @ mf[m]
        rating = float(np.clip(np.round(3.0 + score), 1, 5))
        cats = rng.randint(0, _CATS, rng.randint(1, 4)).tolist()
        title = rng.randint(0, _TITLE_VOCAB, rng.randint(2, 6)).tolist()
        out.append((u, int(rng.randint(0, 2)), int(rng.randint(0, 7)),
                    int(rng.randint(0, _MAX_JOB)), m, cats, title,
                    rating))
    return reader_creator(out)


def train():
    return _make(4096, 8)


def test():
    return _make(512, 9)

