"""MovieLens readers (ref: python/paddle/dataset/movielens.py:
train()/test() yield (user_id, gender_id, age_id, job_id, movie_id,
category_ids, title_ids, rating)). Synthetic with a low-rank
user x movie preference structure the recommender can learn."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

_USERS, _MOVIES, _CATS, _TITLE_VOCAB = 944, 1683, 19, 512
_MAX_JOB = 20


def max_user_id():
    """ref API: paddle.dataset.movielens.max_user_id() -> int."""
    return _USERS


def max_movie_id():
    return _MOVIES


def max_job_id():
    return _MAX_JOB


def movie_categories():
    return {("c%d" % i): i for i in range(_CATS)}


def _make(n, seed):
    rng = np.random.RandomState(seed)
    uf = rng.randn(_USERS, 4)
    mf = rng.randn(_MOVIES, 4)
    out = []
    for _ in range(n):
        u = int(rng.randint(1, _USERS))
        m = int(rng.randint(1, _MOVIES))
        score = uf[u] @ mf[m]
        rating = float(np.clip(np.round(3.0 + score), 1, 5))
        cats = rng.randint(0, _CATS, rng.randint(1, 4)).tolist()
        title = rng.randint(0, _TITLE_VOCAB, rng.randint(2, 6)).tolist()
        out.append((u, int(rng.randint(0, 2)), int(rng.randint(0, 7)),
                    int(rng.randint(0, _MAX_JOB)), m, cats, title,
                    rating))
    return reader_creator(out)


def train():
    return _make(4096, 8)


def test():
    return _make(512, 9)



# ref movielens.py:36 — the canonical MovieLens age buckets
age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """ref: movielens.py:48 — id/title/categories record."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = list(categories)
        self.title = title

    def value(self):
        cats = movie_categories()
        titles = get_movie_title_dict()
        return [self.index, [cats[c] for c in self.categories],
                [titles[w.lower()] for w in self.title.split()]]

    def __str__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")

    __repr__ = __str__


class UserInfo:
    """ref: movielens.py:75 — id/gender/age-bucket/job record."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __str__(self):
        return (f"<UserInfo id({self.index}), "
                f"gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")

    __repr__ = __str__


_MOVIE_TITLE_DICT = None
_MOVIE_INFO = None
_USER_INFO = None


def get_movie_title_dict():
    """ref API: word -> id over the title vocabulary (synthetic ids are
    their own words here)."""
    global _MOVIE_TITLE_DICT
    if _MOVIE_TITLE_DICT is None:
        _MOVIE_TITLE_DICT = {f"t{i}": i for i in range(_TITLE_VOCAB)}
    return _MOVIE_TITLE_DICT


def movie_info():
    """ref API: movie_id -> MovieInfo."""
    global _MOVIE_INFO
    if _MOVIE_INFO is None:
        rng = np.random.RandomState(5)
        cats = list(movie_categories())
        _MOVIE_INFO = {}
        for m in range(1, _MOVIES + 1):
            title = " ".join(
                f"t{int(i)}" for i in rng.randint(0, _TITLE_VOCAB,
                                                  rng.randint(2, 6)))
            chosen = [cats[int(i)]
                      for i in rng.randint(0, _CATS, rng.randint(1, 4))]
            _MOVIE_INFO[m] = MovieInfo(m, chosen, title)
    return _MOVIE_INFO


def user_info():
    """ref API: user_id -> UserInfo."""
    global _USER_INFO
    if _USER_INFO is None:
        rng = np.random.RandomState(6)
        _USER_INFO = {
            u: UserInfo(u, "M" if rng.randint(0, 2) else "F",
                        age_table[int(rng.randint(0, len(age_table)))],
                        int(rng.randint(0, _MAX_JOB)))
            for u in range(1, _USERS + 1)}
    return _USER_INFO
