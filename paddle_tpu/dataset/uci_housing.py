"""UCI housing readers (ref: python/paddle/dataset/uci_housing.py:
train()/test() yield ((13,) float32, (1,) float32)). Synthetic linear
task with noise — fit_a_line trains to low loss on it."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]
_W = np.random.RandomState(99).randn(13).astype("float32")


def _make(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 13).astype("float32")
    y = (x @ _W + 1.5 + rng.randn(n).astype("float32") * 0.1)
    return reader_creator([(xi, yi.reshape(1)) for xi, yi in
                           zip(x, y.astype("float32"))])


def train():
    return _make(404, 2)


def test():
    return _make(102, 3)

