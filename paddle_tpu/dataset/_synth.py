"""Shared synthetic-data helpers for the dataset readers."""
from __future__ import annotations

import numpy as np


def class_mean_images(n, shape, classes, seed, noise=0.35, flat=True,
                      task_seed=None):
    """Separable image-classification data: per-class mean + noise,
    scaled to the reference's [-1, 1] convention.

    ``task_seed`` fixes the class means independently of the sample
    draws, so a train/test PAIR shares one task (a model trained on
    train() generalizes to test(), like the real dataset) while the
    splits remain disjoint draws."""
    rng = np.random.RandomState(seed)
    means_rng = rng if task_seed is None else \
        np.random.RandomState(task_seed)
    means = means_rng.randn(classes, *shape).astype("float32")
    y = rng.randint(0, classes, n)
    x = means[y] + rng.randn(n, *shape).astype("float32") * noise
    x = np.tanh(x)  # into [-1, 1]
    if flat:
        x = x.reshape(n, -1)
    return x.astype("float32"), y.astype("int64")


def zipf_sentences(n, vocab, min_len, max_len, seed, order=2):
    """Markov text with a Zipfian unigram marginal: learnable n-gram
    structure for language-model chapters."""
    rng = np.random.RandomState(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    # deterministic bigram kernel: next word drawn near f(prev)
    shift = rng.randint(1, vocab, size=vocab)
    sents = []
    for _ in range(n):
        L = rng.randint(min_len, max_len + 1)
        w = [int(rng.choice(vocab, p=probs))]
        for _ in range(L - 1):
            if rng.rand() < 0.6:  # predictable transition
                w.append(int((w[-1] + shift[w[-1]]) % vocab))
            else:
                w.append(int(rng.choice(vocab, p=probs)))
        sents.append(w)
    return sents


def reader_creator(samples):
    """paddle.dataset convention: a creator returning a fresh generator."""
    def reader():
        for s in samples:
            yield s

    return reader


def parallel_sentences(n, src_v, trg_v, min_len, max_len, seed):
    """(src, trg) pairs where trg is a learnable mapping of src (ids
    start at 3; 0/1/2 = <s>/<e>/<unk> per the reference convention)."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        L = rng.randint(min_len, max_len + 1)
        src = rng.randint(3, src_v, L)
        trg = (src % (trg_v - 3)) + 3
        out.append((src.astype(np.int64).tolist(),
                    trg.astype(np.int64).tolist()))
    return out


def labeled_sentences(n, vocab, min_len, max_len, seed):
    """Binary-labeled id sequences with class-split vocab halves (same
    separable structure the imdb reader uses)."""
    rng = np.random.RandomState(seed)
    half = vocab // 2
    out = []
    for _ in range(n):
        lab = int(rng.randint(0, 2))
        L = rng.randint(min_len, max_len + 1)
        ids = rng.randint(0, half, L) + (half if lab else 0)
        out.append((ids.astype(np.int64).tolist(), lab))
    return out


def fetch():
    """ref: dataset fetch() — download-ahead hook. Synthetic data is
    generated in-process (zero-egress environment), so there is nothing
    to pre-download; kept so common.fetch_all() and user warm-up scripts
    run unmodified."""
    return None
