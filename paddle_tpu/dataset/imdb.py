"""IMDB sentiment readers (ref: python/paddle/dataset/imdb.py:
word_dict(), train(word_idx)/test(word_idx) yield ([ids], 0/1)).
Synthetic: positive/negative classes draw from shifted vocab regions,
so conv/LSTM sentiment models separate them."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

_VOCAB = 5148  # mirrors the real dict size order


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _make(n, seed, word_idx):
    from ._synth import labeled_sentences

    # randint(16, 64) exclusive == min 16 / max 63 inclusive
    return reader_creator(
        labeled_sentences(n, len(word_idx), 16, 63, seed))


def train(word_idx):
    return _make(1024, 4, word_idx)


def test(word_idx):
    return _make(256, 5, word_idx)



def build_dict(pattern=None, cutoff=0):
    """ref: imdb.py build_dict(pattern, cutoff) — corpus word->id dict.
    The synthetic corpus IS the id space, so this returns the same
    mapping word_dict() serves (cutoff keeps the signature honest: ids
    below frequency cutoff would drop; synthetic frequencies are
    uniform, so nothing drops)."""
    return word_dict()
