"""IMDB sentiment readers (ref: python/paddle/dataset/imdb.py:
word_dict(), train(word_idx)/test(word_idx) yield ([ids], 0/1)).
Synthetic: positive/negative classes draw from shifted vocab regions,
so conv/LSTM sentiment models separate them."""
import numpy as np

from ._synth import reader_creator

_VOCAB = 5148  # mirrors the real dict size order


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _make(n, seed, word_idx):
    rng = np.random.RandomState(seed)
    v = len(word_idx)
    half = v // 2
    out = []
    for _ in range(n):
        lab = int(rng.randint(0, 2))
        L = rng.randint(16, 64)
        base = rng.randint(0, half, L)
        ids = base + (half if lab else 0)
        out.append((ids.astype(np.int64).tolist(), lab))
    return reader_creator(out)


def train(word_idx):
    return _make(1024, 4, word_idx)


def test(word_idx):
    return _make(256, 5, word_idx)
