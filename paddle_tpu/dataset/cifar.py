"""CIFAR readers (ref: python/paddle/dataset/cifar.py: train10/test10,
train100/test100 yield ((3072,) float32, int)). Synthetic."""
from ._synth import fetch  # noqa: F401
from ._synth import class_mean_images, reader_creator

_N_TRAIN, _N_TEST = 2048, 512


def _make(n, classes, seed):
    # task seed per label space: train/test splits share class means
    x, y = class_mean_images(n, (3, 32, 32), classes, seed,
                             task_seed=classes + 90210)
    return reader_creator(list(zip(x, y)))


def train10():
    return _make(_N_TRAIN, 10, 10)


def test10():
    return _make(_N_TEST, 10, 11)


def train100():
    return _make(_N_TRAIN, 100, 12)


def test100():
    return _make(_N_TEST, 100, 13)

