"""VOC2012 segmentation readers (ref: python/paddle/dataset/voc2012.py:
train/test/val yield (image (3, H, W) float32, label mask (H, W) int64)).
Synthetic: blob masks with consistent image/label structure."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

__all__ = ["train", "test", "val"]

_CLASSES = 21
_HW = 64


def _make(n, seed):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n):
        lab = np.zeros((_HW, _HW), np.int64)
        img = rng.randn(3, _HW, _HW).astype("float32") * 0.1
        for _ in range(rng.randint(1, 4)):
            c = rng.randint(1, _CLASSES)
            cy, cx = rng.randint(8, _HW - 8, 2)
            r = rng.randint(4, 12)
            yy, xx = np.mgrid[0:_HW, 0:_HW]
            blob = (yy - cy) ** 2 + (xx - cx) ** 2 < r * r
            lab[blob] = c
            img[:, blob] += (c / _CLASSES) * 2 - 1  # class-coded intensity
        samples.append((np.tanh(img).astype("float32"), lab))
    return reader_creator(samples)


def train():
    return _make(256, 50)


def test():
    return _make(64, 51)


def val():
    return _make(64, 52)

