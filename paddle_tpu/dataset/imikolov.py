"""PTB-style n-gram readers (ref: python/paddle/dataset/imikolov.py:
build_dict(), train(word_idx, n)/test(word_idx, n) yield n-gram tuples).
Synthetic Markov text — word2vec learns its transition structure."""
from ._synth import fetch  # noqa: F401
from ._synth import zipf_sentences, reader_creator

_VOCAB = 2074


def build_dict(min_word_freq=50):
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _make(n_sent, seed, word_idx, n):
    sents = zipf_sentences(n_sent, len(word_idx), n + 2, 24, seed)
    grams = []
    for s in sents:
        for i in range(len(s) - n + 1):
            grams.append(tuple(s[i:i + n]))
    return reader_creator(grams)


def train(word_idx, n):
    return _make(256, 6, word_idx, n)


def test(word_idx, n):
    return _make(64, 7, word_idx, n)

