"""MNIST readers (ref: python/paddle/dataset/mnist.py API: train()/test()
yield ((784,) float32 in [-1,1], int label)). Synthetic — see package doc."""
from ._synth import fetch  # noqa: F401
from ._synth import class_mean_images, reader_creator

_N_TRAIN, _N_TEST = 2048, 512


def _make(n, seed):
    # shared task_seed: train and test draw from ONE set of class
    # means (disjoint from the sample seeds; None would mean per-split)
    x, y = class_mean_images(n, (1, 28, 28), 10, seed,
                             task_seed=90210)
    return reader_creator(list(zip(x, y)))


def train():
    return _make(_N_TRAIN, 0)


def test():
    return _make(_N_TEST, 1)

