"""paddle_tpu.dataset — reader-creator API parity with paddle.dataset.

Ref (capability target): python/paddle/dataset/{mnist,cifar,uci_housing,
imdb,imikolov,movielens,wmt16,conll05}.py — each module exposes
``train()`` / ``test()`` reader creators yielding per-sample tuples.

This environment has zero network egress, so the readers are backed by
DETERMINISTIC SYNTHETIC data with the same sample shapes, dtypes, vocab
structure, and separability properties as the originals (class-mean
images, n-gram text with Zipfian vocab, etc.) — enough to train every
book-chapter model end to end and exercise identical input pipelines.
Swap in the real files by pointing the loaders at a data directory if
one exists.
"""
from . import mnist  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import sentiment  # noqa: F401
from . import mq2007  # noqa: F401
from . import image  # noqa: F401
from . import common  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import wmt16  # noqa: F401
from . import conll05  # noqa: F401

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "wmt16", "conll05"]
