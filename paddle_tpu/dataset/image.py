"""Image utilities (ref: python/paddle/dataset/image.py) — numpy-only
versions of the transform helpers (the reference shells out to cv2)."""
from __future__ import annotations

import numpy as np

__all__ = ["resize_short", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "to_chw"]


def _resize(im, h, w):
    """Nearest-neighbor HWC resize (dependency-free)."""
    H, W = im.shape[:2]
    ys = (np.arange(h) * (H / h)).astype(int).clip(0, H - 1)
    xs = (np.arange(w) * (W / w)).astype(int).clip(0, W - 1)
    return im[ys][:, xs]


def resize_short(im, size):
    """Resize so the short edge equals ``size`` (HWC)."""
    H, W = im.shape[:2]
    if H < W:
        return _resize(im, size, int(W * size / H))
    return _resize(im, int(H * size / W), size)


def center_crop(im, size, is_color=True):
    H, W = im.shape[:2]
    h0 = (H - size) // 2
    w0 = (W - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    H, W = im.shape[:2]
    h0 = rng.randint(0, H - size + 1)
    w0 = rng.randint(0, W - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> crop(+flip when training) -> CHW -> mean-subtract
    (ref: image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        rng2 = rng or np.random
        if rng2.randint(0, 2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype("float32")
    if mean is not None:
        m = np.asarray(mean, "float32")
        im -= m.reshape((-1, 1, 1)) if m.ndim == 1 else m
    return im
