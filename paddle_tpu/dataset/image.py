"""Image utilities (ref: python/paddle/dataset/image.py) — numpy-only
versions of the transform helpers (the reference shells out to cv2)."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["resize_short", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "to_chw",
           "load_image", "load_image_bytes", "load_and_transform",
           "batch_images_from_tar"]


def _resize(im, h, w):
    """Nearest-neighbor HWC resize (dependency-free)."""
    H, W = im.shape[:2]
    ys = (np.arange(h) * (H / h)).astype(int).clip(0, H - 1)
    xs = (np.arange(w) * (W / w)).astype(int).clip(0, W - 1)
    return im[ys][:, xs]


def resize_short(im, size):
    """Resize so the short edge equals ``size`` (HWC)."""
    H, W = im.shape[:2]
    if H < W:
        return _resize(im, size, int(W * size / H))
    return _resize(im, int(H * size / W), size)


def center_crop(im, size, is_color=True):
    H, W = im.shape[:2]
    h0 = (H - size) // 2
    w0 = (W - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    H, W = im.shape[:2]
    h0 = rng.randint(0, H - size + 1)
    w0 = rng.randint(0, W - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    if im.ndim == 2:  # grayscale HW: nothing to transpose (ref guard)
        return im
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> crop(+flip when training) -> CHW -> mean-subtract
    (ref: image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        rng2 = rng or np.random
        if rng2.randint(0, 2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype("float32")
    if mean is not None:
        m = np.asarray(mean, "float32")
        im -= m.reshape((-1, 1, 1)) if m.ndim == 1 else m
    return im


def load_image_bytes(bytes_, is_color=True):
    """ref: image.py:141 — decode an encoded image from bytes (the
    reference uses cv2.imdecode; PIL here) into an HWC uint8 array
    (HW for grayscale)."""
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(bytes_))
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def load_image(file, is_color=True):
    """ref: image.py:167 — load an image file (cv2.imread there, PIL
    here); HWC uint8 (HW for grayscale)."""
    from PIL import Image

    with Image.open(file) as im:
        im = im.convert("RGB" if is_color else "L")
        return np.asarray(im)


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """ref: image.py:383 — load then simple_transform."""
    im = load_image(filename, is_color=is_color)
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color=is_color, mean=mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """ref: image.py:80 — read images out of a tar, pickle them into
    fixed-size batch files (data + label lists) next to the tar, and
    write a meta file listing the batches. Returns the meta path."""
    import pickle
    import tarfile

    out_path = f"{data_file}_{dataset_name}_batch"
    os.makedirs(out_path, exist_ok=True)
    batches = []
    data, labels = [], []

    def flush():
        if not data:
            return
        fname = os.path.join(out_path, f"batch_{len(batches)}")
        with open(fname, "wb") as f:
            pickle.dump({"data": list(data), "label": list(labels)}, f,
                        protocol=4)
        batches.append(fname)
        data.clear()
        labels.clear()

    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if not member.isfile() or member.name not in img2label:
                continue
            raw = tf.extractfile(member).read()
            data.append(raw)
            labels.append(img2label[member.name])
            if len(data) == num_per_batch:
                flush()
    flush()
    meta = os.path.join(out_path, "batch_meta")
    with open(meta, "w") as f:
        f.write("\n".join(batches))
    return meta
