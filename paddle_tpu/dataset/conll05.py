"""CoNLL-05 SRL readers (ref: python/paddle/dataset/conll05.py:
get_dict(), test() yields (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1,
ctx_p2, verb_ids, mark, label_ids)). Synthetic: labels follow word
identity + predicate distance, which a BiLSTM-CRF tagger can learn."""
import numpy as np

from ._synth import fetch  # noqa: F401
from ._synth import reader_creator

_WORDS, _VERBS, _LABELS = 4459, 3162, 59


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_VERBS)}
    label_dict = {("l%d" % i): i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    return np.random.RandomState(17).randn(_WORDS, 32).astype("float32")


def _make(n, seed):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        L = rng.randint(5, 20)
        words = rng.randint(0, _WORDS, L)
        pred_pos = int(rng.randint(0, L))
        verb = int(rng.randint(0, _VERBS))
        mark = [1 if i == pred_pos else 0 for i in range(L)]
        labels = [(int(w) + abs(i - pred_pos)) % _LABELS
                  for i, w in enumerate(words)]
        ctx = words.tolist()

        def shift(k):
            return [ctx[min(max(i + k, 0), L - 1)] for i in range(L)]

        out.append((words.tolist(), shift(-2), shift(-1), shift(0),
                    shift(1), shift(2), [verb] * L, mark, labels))
    return reader_creator(out)


def train():
    return _make(512, 18)


def test():
    return _make(128, 19)

