"""ParamAttr / WeightNormParamAttr (ref: python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from . import initializer as I

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        """Normalize user input (ref: ParamAttr._to_attr): None → default attr,
        False → no parameter, str → named attr, Initializer → attr with it."""
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, I.Initializer):
            return ParamAttr(initializer=arg)
        if arg is False:
            return False
        raise TypeError(f"invalid ParamAttr spec: {arg!r}")


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
