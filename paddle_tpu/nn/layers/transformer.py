"""Transformer layers.

Refs: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/Decoder) and the reference's book ch8 transformer model.

Attention runs through F.sdpa_bhld so the pallas flash-attention kernel can be
swapped in transparently on TPU (ops/pallas/). All projections keep the
(in, out) weight layout for direct MXU mapping.
"""
from __future__ import annotations

import collections

from ...core.tensor import Tensor
from ...ops.manipulation import reshape, transpose, concat
from .. import functional as F
from ..layer import Layer, LayerList
from .common import Linear, Dropout
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])
    # Fixed-size incremental KV cache: (B, H, max_len, D) buffers + a
    # write index. Unlike Cache (which GROWS by concat and so re-traces
    # every step), the static buffer keeps all shapes constant — the form
    # lax.while_loop decode loops require, and the standard TPU KV-cache
    # layout (one dynamic_update_slice per step, no reallocation).
    StaticKVCache = collections.namedtuple("StaticKVCache",
                                           ["k", "v", "idx"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        # (B, L, E) -> (B, H, L, D)
        b, l = x.shape[0], x.shape[1]
        x = reshape(x, [b, l, self.num_heads, self.head_dim])
        return transpose(x, [0, 2, 1, 3])

    def compute_kv(self, key, value):
        return self._split_heads(self.k_proj(key)), \
            self._split_heads(self.v_proj(value))

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k, v = self.compute_kv(key, key if value is None else value)
            return self.StaticCache(k, v)
        if value is not None:
            k, v = self.compute_kv(key, value)
            return self.Cache(k, v)
        # empty incremental cache: decode starts from length 0 and the k/v
        # concat grows it step by step (ref: transformer.py gen_cache)
        import jax.numpy as jnp

        b = key.shape[0]
        empty = Tensor(jnp.zeros((b, self.num_heads, 0, self.head_dim),
                                 key._data.dtype), _internal=True)
        return self.Cache(empty, empty)

    def gen_static_kv_cache(self, batch_size, max_length, dtype="float32"):
        """Zeroed fixed-size incremental cache (see StaticKVCache)."""
        import jax.numpy as jnp

        buf = Tensor(jnp.zeros(
            (batch_size, self.num_heads, max_length, self.head_dim),
            dtype), _internal=True)
        return self.StaticKVCache(buf, buf, jnp.zeros((), jnp.int32))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticKVCache):
            return self._forward_static_kv(q, key, value, attn_mask, cache)
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k, v = self.compute_kv(key, value)
            if isinstance(cache, self.Cache):
                k = concat([cache.k, k], axis=2)
                v = concat([cache.v, v], axis=2)
                cache = self.Cache(k, v)
        out = F.sdpa_bhld(q, k, v, attn_mask=attn_mask,
                          dropout_p=self.dropout, training=self.training)
        b = out.shape[0]
        out = transpose(out, [0, 2, 1, 3])
        out = reshape(out, [b, out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)  # weights not materialized on the fused path
        if isinstance(cache, self.Cache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)

    def _forward_static_kv(self, q, key, value, attn_mask, cache):
        """One incremental step against the fixed-size buffer (shared
        machinery in ``static_kv_attention``)."""
        k_new, v_new = self.compute_kv(key, value)      # (B, H, L, D)
        out, new_cache = static_kv_attention(
            q, k_new, v_new, cache, attn_mask=attn_mask,
            dropout_p=self.dropout, training=self.training)
        b = out.shape[0]
        out = transpose(out, [0, 2, 1, 3])
        out = reshape(out, [b, out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        return out, new_cache


def static_kv_attention(q, k_new, v_new, cache, attn_mask=None,
                        dropout_p=0.0, training=False):
    """Fixed-buffer incremental attention, the jittable decode core:
    write the L new K/V rows at ``idx`` (dynamic_update_slice), attend
    over the whole buffer with a causal+validity mask — query i at
    global position idx+i sees keys j <= idx+i (L=1 per-token decode and
    L=prompt prefill are the same formula). Shapes never change, so the
    step traces once inside lax.while_loop/scan decode loops. Returns
    ((B, H, L, D) attention output, advanced StaticKVCache)."""
    import jax
    import jax.numpy as jnp

    kb = cache.k._data if isinstance(cache.k, Tensor) else cache.k
    vb = cache.v._data if isinstance(cache.v, Tensor) else cache.v
    idx = cache.idx._data if isinstance(cache.idx, Tensor) else cache.idx
    idx = jnp.asarray(idx, jnp.int32)
    L = q._data.shape[2]
    zero = jnp.zeros((), jnp.int32)
    k_upd = jax.lax.dynamic_update_slice(
        kb, k_new._data.astype(kb.dtype), (zero, zero, idx, zero))
    v_upd = jax.lax.dynamic_update_slice(
        vb, v_new._data.astype(vb.dtype), (zero, zero, idx, zero))
    Lmax = kb.shape[2]
    j = jnp.arange(Lmax)[None, :]
    i = jnp.arange(L)[:, None]
    valid = (j <= idx + i).reshape(1, 1, L, Lmax)
    mask_t = Tensor(valid, _internal=True)
    if attn_mask is not None:
        am = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
        if am.dtype == jnp.bool_:
            mask_t = Tensor(jnp.logical_and(valid, am), _internal=True)
        else:  # additive mask: fold the validity window into it
            mask_t = Tensor(
                jnp.where(valid, am.astype(jnp.float32), -1e30),
                _internal=True)
    out = F.sdpa_bhld(q, Tensor(k_upd, _internal=True),
                      Tensor(v_upd, _internal=True), attn_mask=mask_t,
                      dropout_p=dropout_p, training=training)
    new_cache = MultiHeadAttention.StaticKVCache(
        Tensor(k_upd, _internal=True), Tensor(v_upd, _internal=True),
        idx + L)
    return out, new_cache


def _activation(name):
    return getattr(F, name)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(_activation(self.activation)(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, c = mod(output, src_mask=src_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(_activation(self.activation)(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory,
                                               type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [
            copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask,
                             memory_mask=memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask=tgt_mask,
                                memory_mask=memory_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache

    def gen_static_cache(self, memory, max_length):
        """Per-layer (StaticKVCache, StaticCache) pairs for fixed-shape
        (jittable) incremental decode; cross-attention K/V precomputed
        from ``memory`` as usual."""
        b = memory.shape[0]
        dtype = memory._data.dtype
        out = []
        for layer in self.layers:
            inc = layer.self_attn.gen_static_kv_cache(b, max_length, dtype)
            static = layer.cross_attn.gen_cache(
                memory, memory, type=MultiHeadAttention.StaticCache)
            out.append((inc, static))
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        mask = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e30)
        return Tensor(mask.astype(jnp.float32), _internal=True)
