"""Recurrent layers.

Refs: python/paddle/fluid/layers/rnn.py (RNNCell/rnn/birnn),
python/paddle/nn/layer/rnn.py (SimpleRNN/LSTM/GRU),
paddle/fluid/operators/{rnn_op,lstm_op,gru_op}.

TPU design: the whole sequence run is ONE framework op whose kernel is a
``lax.scan`` over time — a single tape node, so forward+backward compile to
one fused XLA while-loop (the reference instead launches cuDNN RNN kernels or
per-step ops). Variable-length sequences are handled by masking against
``sequence_length`` inside the scan — static shapes, MXU-friendly.
"""
from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor
from .. import functional as F
from ..layer import Layer, LayerList
from .. import initializer as I

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _wrap(a):
    return Tensor(a, _internal=True)


@contextlib.contextmanager
def _swap_params(params, arrays):
    """Temporarily rebind cell Parameters to traced arrays so jax.vjp sees
    the params as differentiable inputs of the fused sequence op."""
    old = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, o in zip(params, old):
            p._data = o


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run ``cell`` over time with lax.scan (ref: layers/rnn.py rnn())."""
    x = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
    batch_idx = 1 if time_major else 0
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs, batch_dim_idx=batch_idx)
    states_flat, states_tree = jax.tree_util.tree_flatten(
        initial_states, is_leaf=lambda v: isinstance(v, Tensor))
    params = [p for p in cell.parameters() if p is not None]
    seq = sequence_length

    def kernel(x, seq_len, *flat, time_major, is_reverse, n_state):
        st = [jnp.asarray(s) for s in flat[:n_state]]
        p_arrays = flat[n_state:]
        xs = x if time_major else jnp.swapaxes(x, 0, 1)
        T = xs.shape[0]
        t_idx = jnp.arange(T)
        if is_reverse:
            xs = jnp.flip(xs, axis=0)
            t_idx = jnp.flip(t_idx, axis=0)

        def step(carry, xt_t):
            xt, t = xt_t
            states = jax.tree_util.tree_unflatten(states_tree, list(carry))
            with _swap_params(params, p_arrays), dispatch.no_grad():
                out, new_states = cell(
                    _wrap(xt),
                    jax.tree_util.tree_map(
                        _wrap, states, is_leaf=lambda v: isinstance(v, jax.Array)))
            new_flat = [s._data for s in jax.tree_util.tree_leaves(
                new_states, is_leaf=lambda v: isinstance(v, Tensor))]
            out = out._data
            if seq_len is not None:
                keep = (t < seq_len).reshape((-1,) + (1,) * (out.ndim - 1))
                new_flat = [jnp.where(keep, n, c) for n, c in zip(new_flat, carry)]
                out = jnp.where(keep, out, jnp.zeros_like(out))
            return tuple(new_flat), out

        final, ys = jax.lax.scan(step, tuple(st), (xs, t_idx))
        if is_reverse:
            ys = jnp.flip(ys, axis=0)
        if not time_major:
            ys = jnp.swapaxes(ys, 0, 1)
        return (ys, *final)

    out = dispatch.apply(
        "rnn_scan", kernel, inputs, seq, *states_flat, *params,
        time_major=bool(time_major), is_reverse=bool(is_reverse),
        n_state=len(states_flat))
    ys, final = out[0], list(out[1:])
    final_states = jax.tree_util.tree_unflatten(states_tree, final)
    return ys, final_states


def birnn(cell_fw, cell_bw, inputs, initial_states=None, sequence_length=None,
          time_major=False, **kwargs):
    """ref: layers/rnn.py birnn()."""
    if initial_states is None:
        fw_init = bw_init = None
    else:
        fw_init, bw_init = initial_states
    out_fw, st_fw = rnn(cell_fw, inputs, fw_init, sequence_length,
                        time_major=time_major, is_reverse=False)
    out_bw, st_bw = rnn(cell_bw, inputs, bw_init, sequence_length,
                        time_major=time_major, is_reverse=True)
    from ...ops.manipulation import concat

    return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = (batch_ref._data if isinstance(batch_ref, Tensor)
                 else jnp.asarray(batch_ref)).shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        dtype = dtype or "float32"

        def build(s):
            return Tensor(jnp.full((batch, *s), init_value,
                                   dtype=jnp.dtype(dtype) if isinstance(dtype, str) else dtype),
                          _internal=True)

        if isinstance(shape, tuple) and shape and isinstance(shape[0], (tuple, list)):
            return tuple(build(tuple(s)) for s in shape)
        return build(tuple(shape))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((hidden_size,), attr=bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter((hidden_size,), attr=bias_hh_attr,
                                             is_bias=True, default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = F.tanh if self.activation == "tanh" else F.relu
        gi = F.linear(inputs, self.weight_ih.T, self.bias_ih)
        gh = F.linear(states, self.weight_hh.T, self.bias_hh)
        h = act(gi + gh)
        return h, h


class LSTMCell(RNNCellBase):
    """Gate order i, f, g(cell), o (matches the reference's lstm_op)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((4 * hidden_size,),
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((4 * hidden_size,),
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        gates = F.linear(inputs, self.weight_ih.T, self.bias_ih) + \
            F.linear(h, self.weight_hh.T, self.bias_hh)
        from ...ops.manipulation import split

        i, f, g, o = split(gates, 4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        new_c = f * c + i * g
        new_h = o * F.tanh(new_c)
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    """Gate order r, z, c; h' = z*h + (1-z)*c (ref: gru_op)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((3 * hidden_size,),
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((3 * hidden_size,),
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = states
        from ...ops.manipulation import split

        gi = F.linear(inputs, self.weight_ih.T, self.bias_ih)
        gh = F.linear(h, self.weight_hh.T, self.bias_hh)
        i_r, i_z, i_c = split(gi, 3, axis=-1)
        h_r, h_z, h_c = split(gh, 3, axis=-1)
        r = F.sigmoid(i_r + h_r)
        z = F.sigmoid(i_z + h_z)
        c = F.tanh(i_c + r * h_c)
        new_h = z * h + (1.0 - z) * c
        return new_h, new_h


class RNN(Layer):
    """Generic cell runner (ref: fluid/layers/rnn.py RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        return rnn(self.cell, inputs, initial_states, sequence_length,
                   time_major=self.time_major, is_reverse=self.is_reverse)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        return birnn(self.cell_fw, self.cell_bw, inputs, initial_states,
                     sequence_length, time_major=self.time_major)


class _RNNBase(LayerList):
    """Stacked (and optionally bidirectional) recurrent net."""

    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.direction = direction
        kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                  bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        if activation is not None:
            kw["activation"] = activation
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * self.num_directions
            for _ in range(self.num_directions):
                self.append(type(self).CELL(in_size, hidden_size, **kw))

    def _cell(self, layer, direction):
        return self[layer * self.num_directions + direction]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        D = self.num_directions
        state_comps = 2 if type(self).CELL is LSTMCell else 1
        if initial_states is not None:
            # paddle layout: (num_layers*D, B, H) per state component
            init = initial_states if isinstance(initial_states, (tuple, list)) \
                else (initial_states,)
        else:
            init = None
        out = inputs
        finals = []  # per (layer, direction) final states
        for layer in range(self.num_layers):
            runs = []
            for d in range(D):
                cell = self._cell(layer, d)
                if init is not None:
                    idx = layer * D + d
                    st = tuple(s[idx] for s in init)
                    st = st if state_comps == 2 else st[0]
                else:
                    st = None
                ys, fs = rnn(cell, out, st, sequence_length,
                             time_major=self.time_major, is_reverse=bool(d))
                runs.append(ys)
                finals.append(fs)
            if D == 2:
                from ...ops.manipulation import concat

                out = concat(runs, axis=-1)
            else:
                out = runs[0]
            if self.dropout and layer < self.num_layers - 1:
                out = F.dropout(out, p=self.dropout, training=self.training)
        from ...ops.manipulation import stack

        if state_comps == 2:
            h = stack([f[0] for f in finals], axis=0)
            c = stack([f[1] for f in finals], axis=0)
            return out, (h, c)
        h = stack([f if isinstance(f, Tensor) else f[0] for f in finals], axis=0)
        return out, h


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kw)


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
