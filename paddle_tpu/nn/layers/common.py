"""Common layers (ref: python/paddle/nn/layer/common.py and
fluid/layers/nn.py fc/embedding/dropout/pad/...).
"""
from __future__ import annotations

from ... import ops
from ...ops.manipulation import pad as _pad_op, flatten as _flatten
from .. import functional as F
from ..layer import Layer
from ..param_attr import ParamAttr
from .. import initializer as I

__all__ = [
    "Identity", "Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Embedding", "Flatten", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
    "Upsample", "UpsamplingNearest2D", "UpsamplingBilinear2D",
    "CosineSimilarity", "PairwiseDistance", "Bilinear", "Unfold",
    "PixelShuffle", "ChannelShuffle",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """ref: fc / mul_op; weight stored (in, out) so x@W hits the MXU directly."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.weight.shape[0]}, out={self.weight.shape[1]}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    """ref: lookup_table_op."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx if padding_idx is None or padding_idx >= 0 \
            else num_embeddings + padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            import jax.numpy as jnp

            self.weight._replace(self.weight._data.at[self._padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return _flatten(x, self.start_axis, self.stop_axis)


class _PadNd(Layer):
    nsp = 2

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return _pad_op(x, self.padding, mode=self.mode, value=self.value,
                       data_format=self.data_format or "NC" + "DHW"[-self.nsp:])


class Pad1D(_PadNd):
    nsp = 1


class Pad2D(_PadNd):
    nsp = 2


class Pad3D(_PadNd):
    nsp = 3


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear",
                         align_corners=True, data_format=data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = self.create_parameter((1, out_features), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self.groups)
