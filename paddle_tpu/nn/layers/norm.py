"""Normalization layers (ref: python/paddle/nn/layer/norm.py,
fluid/dygraph/nn.py BatchNorm; kernels: batch_norm_op.cc, layer_norm_op.cc).

BatchNorm running stats are Buffers updated functionally each train step —
no in-place device mutation, so the layer stays jit-compatible. SyncBatchNorm
degenerates to BatchNorm on a single device; under a data-parallel Mesh the
batch axis is sharded and XLA's reduction over it IS the cross-replica sync.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..layer import Layer
from .. import initializer as I

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32), _internal=True))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32), _internal=True))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid.dygraph.BatchNorm-compatible (act fused on top)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kw):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr,
                         data_format=data_layout,
                         use_global_stats=use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", **kw):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCL", **kw)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", **kw):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCDHW", **kw)


class SyncBatchNorm(_BatchNormBase):
    """Under dp sharding the batch-axis reduction is a cross-replica psum
    inserted by XLA — no NCCL sync op needed (ref: sync_batch_norm_op.cu)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            out.weight, out.bias = layer.weight, layer.bias
            out.register_buffer("_mean", layer._mean)
            out.register_buffer("_variance", layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (ref: spectral_norm_op.cc)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(np.random.randn(h).astype(np.float32), _internal=False))
        self.register_buffer("weight_v", Tensor(np.random.randn(w).astype(np.float32), _internal=False))

    def forward(self, weight):
        from ...ops._base import apply, register

        @register("spectral_norm")
        def _sn(w, u, v, *, dim, power_iters, eps):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(power_iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply("spectral_norm", weight, self.weight_u, self.weight_v,
                     dim=self._dim, power_iters=self._power_iters,
                     eps=self._eps)
