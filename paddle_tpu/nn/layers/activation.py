"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer
from .. import initializer as I

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "CELU", "SELU", "GELU",
    "Sigmoid", "LogSigmoid", "Tanh", "Tanhshrink", "Softmax", "LogSoftmax",
    "Softplus", "Softshrink", "Softsign", "Swish", "Silu", "Mish", "Hardtanh",
    "Hardshrink", "Hardsigmoid", "Hardswish", "ThresholdedReLU", "Maxout",
    "GLU",
]


def _simple(fname, **fixed):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(F, fname)(x, **fixed)

    _Act.__name__ = fname.capitalize()
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
Sigmoid = _simple("sigmoid")
LogSigmoid = _simple("log_sigmoid")
Tanh = _simple("tanh")
Tanhshrink = _simple("tanhshrink")
Softsign = _simple("softsign")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)
