"""Pooling layers (ref: python/paddle/nn/layer/pooling.py, pool_op.cc)."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
]


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, exclusive=True, divisor_override=None,
                 data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
