"""Convolution layers (ref: python/paddle/nn/layer/conv.py,
fluid/layers/nn.py conv2d/conv2d_transpose; kernels: conv_op.cc).
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..layer import Layer
from .. import initializer as I

__all__ = [
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _ConvNd(Layer):
    nsp = 2
    transposed = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, self.nsp)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._output_padding = output_padding
        if self.transposed:
            shape = (in_channels, out_channels // groups, *self._kernel_size)
        else:
            shape = (out_channels, in_channels // groups, *self._kernel_size)
        fan_in = in_channels * int(np.prod(self._kernel_size)) // groups
        std = (2.0 / fan_in) ** 0.5
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.Normal(0.0, std))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    nsp = 1

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups)


class Conv2D(_ConvNd):
    nsp = 2

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups)


class Conv3D(_ConvNd):
    nsp = 3

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups)


class Conv1DTranspose(_ConvNd):
    nsp = 1
    transposed = True

    def forward(self, x, output_size=None):
        from ...ops.conv import conv1d_transpose

        return conv1d_transpose(x, self.weight, self.bias, stride=self._stride,
                                padding=self._padding, dilation=self._dilation,
                                groups=self._groups,
                                output_padding=self._output_padding)


class Conv2DTranspose(_ConvNd):
    nsp = 2
    transposed = True

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias,
                                  stride=self._stride, padding=self._padding,
                                  dilation=self._dilation, groups=self._groups,
                                  output_padding=self._output_padding)


class Conv3DTranspose(_ConvNd):
    nsp = 3
    transposed = True

    def forward(self, x, output_size=None):
        from ...ops.conv import conv3d_transpose

        return conv3d_transpose(x, self.weight, self.bias, stride=self._stride,
                                padding=self._padding, dilation=self._dilation,
                                groups=self._groups,
                                output_padding=self._output_padding)
