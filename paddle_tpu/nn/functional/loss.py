"""Loss functions.

Refs: python/paddle/fluid/layers/loss.py (cross_entropy,
softmax_with_cross_entropy, square_error_cost, warpctc, ...),
paddle/fluid/operators/{softmax_with_cross_entropy_op,bce_loss_op,
smooth_l1_loss_op,kldiv_loss_op,warpctc_op,...}.

All losses compute in float32 internally (bf16-safe on TPU) and support the
reference's reduction modes. CTC is a pure lax.scan alpha recursion — no
cuDNN/warpctc handoff; the whole loss fuses into the training step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._base import register, apply, unwrap

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "kl_div",
    "binary_cross_entropy", "binary_cross_entropy_with_logits", "mse_loss",
    "l1_loss", "smooth_l1_loss", "margin_ranking_loss", "cosine_embedding_loss",
    "ctc_loss", "square_error_cost", "log_loss", "sigmoid_focal_loss",
    "hinge_embedding_loss", "triplet_margin_loss", "npair_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# -- cross entropy ----------------------------------------------------------


def _fused_ce_ok(logits, label, weight, axis, use_softmax, label_smoothing):
    """Route to the pallas fused softmax-CE kernel for the common LM-head
    case: 2D (N, V) logits, hard int labels, no class weights."""
    from ...ops import pallas as pk

    return (pk.enabled() and weight is None and use_softmax and
            label_smoothing == 0.0 and axis in (-1, logits.ndim - 1) and
            logits.ndim == 2 and label.ndim in (1, 2) and
            logits.shape[0] % 8 == 0 and logits.shape[1] % 128 == 0)


@register("cross_entropy_hard")
def _ce_hard(logits, label, weight, *, axis, ignore_index, reduction,
             use_softmax, label_smoothing):
    if _fused_ce_ok(logits, label, weight, axis, use_softmax,
                    label_smoothing):
        from ...ops import pallas as pk

        lab = label if label.ndim == 1 else jnp.squeeze(label, axis=-1)
        loss = pk.softmax_cross_entropy(logits, lab, int(ignore_index),
                                        pk.auto_interpret())
        if reduction == "mean":
            valid = (lab != ignore_index).astype(jnp.float32)
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-12)
        return _reduce(loss, reduction)
    lf = logits.astype(jnp.float32)
    n_cls = lf.shape[axis]
    logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(lf, 1e-12))
    label = label.astype(jnp.int32)
    if label.ndim == logp.ndim:  # (..., 1) trailing dim, fluid-style
        label = jnp.squeeze(label, axis=axis)
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(
        jnp.moveaxis(logp, axis, -1), safe[..., None], axis=-1)[..., 0]
    if label_smoothing > 0.0:
        mean_logp = jnp.mean(jnp.moveaxis(logp, axis, -1), axis=-1)
        picked = (1.0 - label_smoothing) * picked + label_smoothing * mean_logp
    loss = -picked
    if weight is not None:
        w = jnp.take(weight.astype(jnp.float32), safe, axis=0)
    else:
        w = jnp.ones_like(loss)
    w = jnp.where(valid, w, 0.0)
    loss = loss * w
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@register("cross_entropy_soft")
def _ce_soft(logits, label, *, axis, reduction, use_softmax, label_smoothing):
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(lf, 1e-12))
    lab = label.astype(jnp.float32)
    if label_smoothing > 0.0:
        lab = lab * (1.0 - label_smoothing) + label_smoothing / lab.shape[axis]
    loss = -jnp.sum(lab * logp, axis=axis)
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    if soft_label:
        return apply("cross_entropy_soft", input, label, axis=axis,
                     reduction=reduction, use_softmax=bool(use_softmax),
                     label_smoothing=float(label_smoothing))
    return apply("cross_entropy_hard", input, label, weight, axis=axis,
                 ignore_index=int(ignore_index), reduction=reduction,
                 use_softmax=bool(use_softmax),
                 label_smoothing=float(label_smoothing))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """Fluid-style: per-example loss with trailing singleton dim kept."""
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis if axis < 0 else axis)
    if return_softmax:
        from ...ops.activation import softmax

        return loss, softmax(logits, axis=axis)
    return loss


@register("nll_loss")
def _nll(logp, label, weight, *, ignore_index, reduction):
    label = label.astype(jnp.int32)
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    lp = jnp.moveaxis(logp.astype(jnp.float32), 1, -1) if logp.ndim > 2 else logp.astype(jnp.float32)
    picked = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
    w = jnp.take(weight.astype(jnp.float32), safe, axis=0) if weight is not None \
        else jnp.ones_like(picked)
    w = jnp.where(valid, w, 0.0)
    loss = -picked * w
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return apply("nll_loss", input, label, weight,
                 ignore_index=int(ignore_index), reduction=reduction)


@register("kl_div")
def _kl_div(logp, target, *, reduction):
    t = target.astype(jnp.float32)
    loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - logp.astype(jnp.float32))
    if reduction == "batchmean":
        return jnp.sum(loss) / logp.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    return apply("kl_div", input, label, reduction=reduction)


# -- regression -------------------------------------------------------------


@register("mse_loss")
def _mse(x, y, *, reduction):
    return _reduce(jnp.square(x.astype(jnp.float32) - y.astype(jnp.float32)), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss", input, label, reduction=reduction)


@register("l1_loss")
def _l1(x, y, *, reduction):
    return _reduce(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss", input, label, reduction=reduction)


@register("smooth_l1_loss")
def _smooth_l1(x, y, *, reduction, delta):
    d = x.astype(jnp.float32) - y.astype(jnp.float32)
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return apply("smooth_l1_loss", input, label, reduction=reduction,
                 delta=float(delta))


@register("square_error_cost")
def _sec(x, y):
    return jnp.square(x - y)


def square_error_cost(input, label):
    return apply("square_error_cost", input, label)


@register("log_loss")
def _log_loss(x, y, *, epsilon):
    xf = x.astype(jnp.float32)
    return -y * jnp.log(xf + epsilon) - (1.0 - y) * jnp.log(1.0 - xf + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply("log_loss", input, label, epsilon=float(epsilon))


# -- binary -----------------------------------------------------------------


@register("bce")
def _bce(x, y, w, *, reduction):
    xf = jnp.clip(x.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
    loss = -(y * jnp.log(xf) + (1.0 - y) * jnp.log(1.0 - xf))
    if w is not None:
        loss = loss * w.astype(jnp.float32)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    return apply("bce", input, label, weight, reduction=reduction)


@register("bce_logits")
def _bce_logits(x, y, w, pos_w, *, reduction):
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    # stable: max(x,0) - x*y + log(1+exp(-|x|)); pos_weight scales the y term
    log_sig = jax.nn.log_sigmoid(xf)
    log_sig_neg = jax.nn.log_sigmoid(-xf)
    if pos_w is not None:
        loss = -(pos_w * yf * log_sig + (1.0 - yf) * log_sig_neg)
    else:
        loss = -(yf * log_sig + (1.0 - yf) * log_sig_neg)
    if w is not None:
        loss = loss * w.astype(jnp.float32)
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return apply("bce_logits", logit, label, weight, pos_weight,
                 reduction=reduction)


@register("sigmoid_focal_loss")
def _focal(x, y, norm, *, alpha, gamma, reduction):
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    p = jax.nn.sigmoid(xf)
    ce = -(yf * jax.nn.log_sigmoid(xf) + (1.0 - yf) * jax.nn.log_sigmoid(-xf))
    p_t = p * yf + (1.0 - p) * (1.0 - yf)
    a_t = alpha * yf + (1.0 - alpha) * (1.0 - yf)
    loss = a_t * ((1.0 - p_t) ** gamma) * ce
    if norm is not None:
        loss = loss / norm
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return apply("sigmoid_focal_loss", logit, label, normalizer,
                 alpha=float(alpha), gamma=float(gamma), reduction=reduction)


# -- ranking / margin -------------------------------------------------------


@register("margin_ranking_loss")
def _margin_rank(x, y, label, *, margin, reduction):
    loss = jnp.maximum(0.0, -label * (x - y) + margin)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply("margin_ranking_loss", input, other, label,
                 margin=float(margin), reduction=reduction)


@register("cosine_embedding_loss")
def _cos_embed(x1, x2, label, *, margin, reduction):
    dot = jnp.sum(x1 * x2, axis=-1)
    n1 = jnp.sqrt(jnp.maximum(jnp.sum(x1 * x1, axis=-1), 1e-12))
    n2 = jnp.sqrt(jnp.maximum(jnp.sum(x2 * x2, axis=-1), 1e-12))
    cos = dot / (n1 * n2)
    loss = jnp.where(label > 0, 1.0 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    return apply("cosine_embedding_loss", input1, input2, label,
                 margin=float(margin), reduction=reduction)


@register("hinge_embedding_loss")
def _hinge_embed(x, label, *, margin, reduction):
    loss = jnp.where(label > 0, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply("hinge_embedding_loss", input, label, margin=float(margin),
                 reduction=reduction)


@register("triplet_margin_loss")
def _triplet(a, p, n, *, margin, p_norm, epsilon, swap, reduction):
    def dist(u, v):
        return jnp.sum(jnp.abs(u - v + epsilon) ** p_norm, axis=-1) ** (1.0 / p_norm)

    d_ap = dist(a, p)
    d_an = dist(a, n)
    if swap:
        d_an = jnp.minimum(d_an, dist(p, n))
    return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    return apply("triplet_margin_loss", input, positive, negative,
                 margin=float(margin), p_norm=float(p), epsilon=float(epsilon),
                 swap=bool(swap), reduction=reduction)


@register("npair_loss")
def _npair(anchor, positive, labels, *, l2_reg):
    sim = jnp.matmul(anchor, positive.T)
    lab = labels.reshape(-1)
    target = (lab[:, None] == lab[None, :]).astype(jnp.float32)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1))
                    + jnp.mean(jnp.sum(positive * positive, axis=1))) * 0.25
    return ce + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return apply("npair_loss", anchor, positive, labels, l2_reg=float(l2_reg))


# -- CTC --------------------------------------------------------------------


@register("ctc_loss")
def _ctc(log_probs, labels, input_lengths, label_lengths, *, blank, reduction,
         norm_by_times):
    """CTC forward (alpha) recursion in log space, batched over B.

    log_probs: (T, B, C) log-softmax scores; labels: (B, S) int.
    The recursion runs as a lax.scan over T — static shapes, fully fused;
    this is the TPU-correct replacement for warpctc (ref: warpctc_op.cc).
    """
    T, B, C = log_probs.shape
    S = labels.shape[1]
    lp = log_probs.astype(jnp.float32)
    labels = labels.astype(jnp.int32)
    neg_inf = jnp.float32(-1e30)

    # extended label sequence with interleaved blanks: length 2S+1
    ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths.astype(jnp.int32) + 1

    # transition mask: alpha[s] may come from s, s-1, and s-2 when
    # ext[s] != blank and ext[s] != ext[s-2]
    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (~same_as_prev2)

    def emit(t_lp, s_idx):
        # gather per-position emission scores: (B, 2S+1)
        return jnp.take_along_axis(t_lp, ext, axis=1)

    init = jnp.full((B, 2 * S + 1), neg_inf)
    init = init.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
    init = init.at[:, 1].set(jnp.where(ext_len > 1,
                                       lp[0, jnp.arange(B), ext[:, 1]], neg_inf))

    def step(alpha, t_lp):
        shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(can_skip, shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + emit(t_lp, None)
        return new_alpha, None

    # sequences shorter than T stop at their own input_length: keep per-step
    # alphas and select at t = input_length - 1
    def step_keep(alpha, t_lp):
        new_alpha, _ = step(alpha, t_lp)
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step_keep, init, lp[1:])
    alphas = jnp.concatenate([init[None], alphas], axis=0)  # (T, B, 2S+1)
    t_idx = jnp.clip(input_lengths.astype(jnp.int32) - 1, 0, T - 1)
    final = alphas[t_idx, jnp.arange(B)]  # (B, 2S+1)
    last = jnp.take_along_axis(final, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(final, jnp.maximum(ext_len - 2, 0)[:, None],
                                axis=1)[:, 0]
    loss = -jnp.logaddexp(last, jnp.where(ext_len > 1, last2, neg_inf))
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(jnp.float32), 1.0)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths.astype(jnp.float32), 1.0))
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    return apply("ctc_loss", log_probs, labels, input_lengths, label_lengths,
                 blank=int(blank), reduction=reduction,
                 norm_by_times=bool(norm_by_times))
