"""paddle_tpu.nn.functional — the functional API surface.

Mirrors ``paddle.nn.functional`` (ref: python/paddle/nn/functional/ +
fluid/layers/{nn,loss}.py), aggregating the op library plus nn-specific
functionals (linear, embedding, losses, attention).
"""
from ...ops.activation import (  # noqa: F401
    relu, relu6, sigmoid, tanh, softmax, log_softmax, gelu, leaky_relu, elu,
    celu, selu, prelu, hardtanh, hardshrink, softshrink, thresholded_relu,
    softplus, softsign, silu, swish, mish, hardswish, hardsigmoid, tanhshrink,
    log_sigmoid, gumbel_softmax, maxout, glu,
)
from ...ops.conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
    max_pool1d, max_pool2d,
    max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d, adaptive_avg_pool1d,
    adaptive_avg_pool2d, adaptive_max_pool1d, adaptive_max_pool2d,
    interpolate, pixel_shuffle, unfold,
    grid_sample, affine_grid,  # 2.x paddle.nn.functional homes
)
from ...ops.norm_ops import (  # noqa: F401
    batch_norm, layer_norm, group_norm, instance_norm, normalize,
    local_response_norm,
)
from ...ops.random_ops import (  # noqa: F401
    dropout, dropout2d, dropout3d, alpha_dropout, channel_shuffle,
)
from ...ops.manipulation import pad  # noqa: F401
from ...ops.sequence import sequence_mask  # noqa: F401
from .common import (  # noqa: F401
    linear, embedding, one_hot, cosine_similarity, pairwise_distance,
    label_smooth, bilinear,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, kl_div,
    binary_cross_entropy, binary_cross_entropy_with_logits, mse_loss, l1_loss,
    smooth_l1_loss, margin_ranking_loss, cosine_embedding_loss, ctc_loss,
    square_error_cost, log_loss, sigmoid_focal_loss, hinge_embedding_loss,
    triplet_margin_loss, npair_loss,
)
from .attention import scaled_dot_product_attention, sdpa_bhld  # noqa: F401

upsample = interpolate

__all__ = [n for n in dir() if not n.startswith("_")]
