"""Common functional ops: linear, embedding, similarity, label smoothing.

Refs: python/paddle/fluid/layers/nn.py (fc, embedding, cos_sim,
label_smooth), paddle/fluid/operators/{mul_op,lookup_table_op,cos_sim_op}.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._base import register, apply, unwrap

__all__ = [
    "linear", "embedding", "one_hot", "cosine_similarity",
    "pairwise_distance", "label_smooth", "bilinear", "class_center_sample",
]


@register("linear")
def _linear(x, w, b):
    y = jnp.matmul(x, w)
    return y + b


@register("linear_nobias")
def _linear_nobias(x, w):
    return jnp.matmul(x, w)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W stored (in_features, out_features) — same layout
    as the reference's mul_op so state_dicts transfer directly; the matmul
    maps straight onto the MXU with no transpose."""
    if bias is None:
        return apply("linear_nobias", x, weight)
    return apply("linear", x, weight, bias)


@register("embedding")
def _embedding(w, ids, *, padding_idx):
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    del sparse  # XLA gathers are always "dense"; grad is a scatter-add
    return apply("embedding", weight, x, padding_idx=padding_idx)


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh

    return _oh(x, num_classes)


@register("cosine_similarity")
def _cos_sim(x1, x2, *, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return apply("cosine_similarity", x1, x2, axis=axis, eps=float(eps))


@register("pairwise_distance")
def _pairwise_distance(x, y, *, p, epsilon, keepdim):
    d = x - y + epsilon
    return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return apply("pairwise_distance", x, y, p=float(p), epsilon=float(epsilon),
                 keepdim=bool(keepdim))


@register("label_smooth")
def _label_smooth(label, *, epsilon):
    k = label.shape[-1]
    return label * (1.0 - epsilon) + epsilon / k


@register("label_smooth_prior")
def _label_smooth_prior(label, prior, *, epsilon):
    return label * (1.0 - epsilon) + epsilon * prior


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return apply("label_smooth_prior", label, prior_dist, epsilon=float(epsilon))
    return apply("label_smooth", label, epsilon=float(epsilon))


@register("bilinear")
def _bilinear(x1, x2, w, b):
    # w: (out, in1, in2)
    y = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    return y if b is None else y + b


@register("bilinear_nobias")
def _bilinear_nobias(x1, x2, w):
    return jnp.einsum("bi,oij,bj->bo", x1, w, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    if bias is None:
        return apply("bilinear_nobias", x1, x2, weight)
    return apply("bilinear", x1, x2, weight, bias)


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample requires dynamic-size outputs, which cannot be "
        "compiled for TPU; use ParallelCrossEntropy (dist/tp_layers.py) instead")
