"""Attention primitives.

Dense reference implementation of scaled-dot-product attention; the pallas
flash-attention kernel (ops/pallas/flash_attention.py) is substituted on TPU
for long sequences. Ref: the reference builds attention from primitive ops in
its transformer models (book ch8 / ERNIE); there is no fused kernel to port —
this is the TPU-native design point.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import random as prandom
from ...core.tensor import Tensor
from ...ops._base import register, apply

__all__ = ["scaled_dot_product_attention", "sdpa_bhld"]


def _flash_ok(q, k, dropout_p, mask):
    """Use the pallas flash kernel when it applies: no mask/dropout (the
    kernel handles causal internally) and MXU-friendly shapes."""
    from ...ops import pallas as pk

    if not pk.enabled() or mask is not None or dropout_p > 0.0:
        return False
    Lq, D = q.shape[-2], q.shape[-1]
    Lk = k.shape[-2]
    return Lq % 128 == 0 and Lk % 128 == 0 and D % 64 == 0 and D <= 256


@register("sdpa")
def _sdpa(q, k, v, mask, key, *, scale, is_causal, dropout_p):
    # q,k,v: (B, H, L, D). Softmax in f32 for bf16 inputs.
    if _flash_ok(q, k, dropout_p, mask):
        from ...ops import pallas as pk

        return pk.flash_attention(q, k, v, bool(is_causal), float(scale),
                                  128, pk.auto_interpret())
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if is_causal:
        Lq, Lk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)
        scores = jnp.where(causal, scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask.astype(jnp.float32)
    p = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and key is not None:
        # dropout on the attention *weights* (reference semantics), before
        # the V matmul, with upscale-in-train normalization
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def sdpa_bhld(query, key, value, attn_mask=None, scale=None, is_causal=False,
              dropout_p=0.0, training=True):
    """(B, H, L, D) layout — internal form used by nn layers."""
    d = query.shape[-1] if not hasattr(query, "_data") else query._data.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    use_drop = dropout_p > 0.0 and training
    rng = Tensor(prandom.next_key(), _internal=True) if use_drop else None
    return apply("sdpa", query, key, value, attn_mask, rng,
                 scale=float(scale), is_causal=bool(is_causal),
                 dropout_p=float(dropout_p) if use_drop else 0.0)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """Paddle 2.x layout (B, L, H, D)."""
    from ...ops.manipulation import transpose

    q = transpose(query, [0, 2, 1, 3])
    k = transpose(key, [0, 2, 1, 3])
    v = transpose(value, [0, 2, 1, 3])
    out = sdpa_bhld(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                    dropout_p=dropout_p, training=training)
    return transpose(out, [0, 2, 1, 3])
